"""Cold-start resilience tests (ISSUE 4): the verified commit-coupled
durable checkpoint format, the recovery scan + quarantine, disk chaos
(torn writes / bit-flips / ENOSPC / stalled IO), the AsyncCheckpointer
stall watchdog, Manager commit coupling + cold start, and the 2-group
divergent-cold-start convergence acceptance (groups recovered from
different on-disk steps end bitwise identical via the existing heal
path). The seeded kill-all→recover soak rides ``scripts/test.sh
cold-start`` (markers ``cold_start`` + ``slow`` + ``nightly``)."""

import os
import time
from unittest.mock import MagicMock, patch

import jax.numpy as jnp
import numpy as np
import pytest

from test_manager import make_manager, quorum_result
from torchft_tpu import chaos as chaos_mod
from torchft_tpu import checkpoint_io as cio
from torchft_tpu.chaos import ChaosSchedule, EndpointChaos, parse_spec
from torchft_tpu.checkpoint_io import (
    AsyncCheckpointer,
    CheckpointCorruptError,
    CheckpointUnverifiableError,
)


def user_state(val=1.0):
    return {
        "params": {"w": jnp.full((8, 8), val), "b": jnp.zeros((4,))},
        "opt": [jnp.ones((2,)), np.int64(3)],
    }


def _flip_at(path, off):
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))


def _first_leaf_offset(path):
    """Absolute offset of the first array leaf's first payload byte."""
    with open(path, "rb") as f:
        _, mf, payload_start = cio._open_verified(f)
    return payload_start + int(mf["preamble_len"])


class TestDurableFormat:
    def test_head_records_provenance(self, tmp_path):
        path = str(tmp_path / "ckpt_7")
        cio.save(path, user_state(), {"step": 7, "batches_committed": 21},
                 meta={"quorum_id": 3, "replica_id": "g0",
                       "committed": True})
        head = cio.read_meta(path)
        assert head["format"] == cio.FORMAT
        assert head["step"] == 7
        assert head["batches_committed"] == 21
        assert head["quorum_id"] == 3
        assert head["replica_id"] == "g0"
        assert head["committed"] is True

    def test_verify_ok_and_load_round_trip(self, tmp_path):
        path = str(tmp_path / "ckpt_2")
        cio.save(path, user_state(2.5), {"step": 2,
                                         "batches_committed": 4})
        assert cio.verify(path)["step"] == 2
        user, mgr = cio.load(path, target=user_state(), device_put=False)
        np.testing.assert_array_equal(user["params"]["w"],
                                      np.full((8, 8), 2.5))
        assert mgr == {"step": 2, "batches_committed": 4}

    def test_legacy_is_unverifiable_but_loads(self, tmp_path):
        from torchft_tpu.serialization import save_pytree

        path = str(tmp_path / "ckpt_3")
        with open(path, "wb") as f:
            f.write(save_pytree(
                {"user": user_state(), "torchft": {"step": 3,
                                                   "batches_committed": 3}}))
        with pytest.raises(CheckpointUnverifiableError):
            cio.verify(path)
        _, mgr = cio.load(path, target=user_state(), device_put=False)
        assert mgr["step"] == 3


class TestVerifiedLoad:
    def test_payload_flip_detected_before_device_put(self, tmp_path,
                                                     monkeypatch):
        """A corrupt leaf is caught by its digest BEFORE any device_put:
        the acceptance invariant that unverified bytes never reach the
        device."""
        path = str(tmp_path / "ckpt_1")
        cio.save(path, user_state(), {"step": 1, "batches_committed": 1})
        _flip_at(path, _first_leaf_offset(path))

        calls = []
        real = cio.device_put_like
        monkeypatch.setattr(cio, "device_put_like",
                            lambda a, t: calls.append(1) or real(a, t))
        with pytest.raises(CheckpointCorruptError, match="digest"):
            cio.load(path, target=user_state())
        assert calls == []  # the flipped first leaf was never placed

    def test_head_flip_detected(self, tmp_path):
        path = str(tmp_path / "ckpt_1")
        cio.save(path, user_state(), {"step": 1, "batches_committed": 1})
        # flip inside the json head (right after magic + length)
        _flip_at(path, len(cio._CKPT_MAGIC) + 4 + 5)
        with pytest.raises(CheckpointCorruptError):
            cio.verify(path)

    def test_preamble_flip_detected(self, tmp_path):
        """The payload preamble json carries py-leaf VALUES inline (step
        counters): a flip there must fail BOTH verify() and load(), not
        just verify — otherwise a corrupted scalar loads silently while
        every array leaf checks out."""
        path = str(tmp_path / "ckpt_1")
        cio.save(path, user_state(), {"step": 1, "batches_committed": 1})
        _flip_at(path, _first_leaf_offset(path) - 3)
        with pytest.raises(CheckpointCorruptError):
            cio.verify(path)
        with pytest.raises(CheckpointCorruptError):
            cio.load(path, target=user_state(), device_put=False)

    def test_truncation_detected(self, tmp_path):
        path = str(tmp_path / "ckpt_1")
        cio.save(path, user_state(), {"step": 1, "batches_committed": 1})
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) - 7)
        with pytest.raises(CheckpointCorruptError):
            cio.verify(path)
        with pytest.raises(CheckpointCorruptError):
            cio.load(path, target=user_state(), device_put=False)


class TestRecover:
    def test_falls_back_past_corrupt_and_quarantines(self, tmp_path):
        good = str(tmp_path / "ckpt_5")
        cio.save(good, user_state(5.0), {"step": 5,
                                         "batches_committed": 5})
        bad = str(tmp_path / "ckpt_8")
        cio.save(bad, user_state(8.0), {"step": 8, "batches_committed": 8})
        _flip_at(bad, _first_leaf_offset(bad))

        stats = {}
        assert cio.recover(str(tmp_path), stats=stats) == good
        assert stats["ckpt_corrupt_quarantined"] == 1
        assert stats["ckpt_recover_fallbacks"] == 1
        assert os.path.exists(bad + ".corrupt")
        assert not os.path.exists(bad)
        # the quarantined file is no longer a candidate for anything
        assert cio.latest(str(tmp_path)) == good

    def test_zero_byte_newest_never_a_candidate(self, tmp_path):
        good = str(tmp_path / "ckpt_2")
        cio.save(good, user_state(), {"step": 2, "batches_committed": 2})
        (tmp_path / "ckpt_9").write_bytes(b"")
        assert cio.latest(str(tmp_path)) == good
        assert cio.recover(str(tmp_path)) == good

    def test_uncommitted_snapshot_skipped(self, tmp_path):
        cio.save(str(tmp_path / "ckpt_1"), user_state(1.0),
                 {"step": 1, "batches_committed": 1})
        cio.save(str(tmp_path / "ckpt_4"), user_state(4.0),
                 {"step": 4, "batches_committed": 4},
                 meta={"committed": False})
        stats = {}
        assert cio.recover(str(tmp_path), stats=stats) == str(
            tmp_path / "ckpt_1")
        assert stats["ckpt_recover_fallbacks"] == 1
        assert stats["ckpt_corrupt_quarantined"] == 0
        assert os.path.exists(tmp_path / "ckpt_4")  # not quarantined

    def test_legacy_skipped_without_quarantine(self, tmp_path):
        from torchft_tpu.serialization import save_pytree

        cio.save(str(tmp_path / "ckpt_1"), user_state(),
                 {"step": 1, "batches_committed": 1})
        legacy = tmp_path / "ckpt_6"
        legacy.write_bytes(save_pytree(
            {"user": user_state(),
             "torchft": {"step": 6, "batches_committed": 6}}))
        assert cio.recover(str(tmp_path)) == str(tmp_path / "ckpt_1")
        assert legacy.exists()  # skipped, not quarantined

    def test_legacy_only_dir_falls_back_instead_of_fresh_start(
            self, tmp_path):
        """Upgrading a job whose directory holds ONLY legacy (pre-v2)
        checkpoints must resume from the newest one, not silently
        restart training from scratch."""
        from torchft_tpu.serialization import save_pytree

        for step in (3, 9):
            (tmp_path / f"ckpt_{step}").write_bytes(save_pytree(
                {"user": user_state(float(step)),
                 "torchft": {"step": step, "batches_committed": step}}))
        stats = {}
        got = cio.recover(str(tmp_path), stats=stats)
        assert got == str(tmp_path / "ckpt_9")
        assert stats["ckpt_recover_legacy"] == 1
        _, mgr = cio.load(got, target=user_state(), device_put=False)
        assert mgr["step"] == 9
        # opt-out restores strict behavior
        assert cio.recover(str(tmp_path), allow_legacy=False) is None

    def test_torn_legacy_never_the_last_resort(self, tmp_path):
        """A TRUNCATED legacy file still starts with the TFTPTREE magic
        (unverifiable, not corrupt) — the legacy last resort must skip
        it for an older structurally-whole one instead of handing
        load() a file that crashes."""
        from torchft_tpu.serialization import save_pytree

        good = save_pytree({"user": user_state(3.0),
                            "torchft": {"step": 3,
                                        "batches_committed": 3}})
        (tmp_path / "ckpt_3").write_bytes(good)
        (tmp_path / "ckpt_9").write_bytes(good[:len(good) // 2])  # torn
        got = cio.recover(str(tmp_path))
        assert got == str(tmp_path / "ckpt_3")
        _, mgr = cio.load(got, target=user_state(), device_put=False)
        assert mgr["step"] == 3

    def test_quarantine_false_counts_nothing_moved(self, tmp_path):
        good = str(tmp_path / "ckpt_1")
        cio.save(good, user_state(), {"step": 1, "batches_committed": 1})
        bad = str(tmp_path / "ckpt_2")
        cio.save(bad, user_state(), {"step": 2, "batches_committed": 2})
        _flip_at(bad, _first_leaf_offset(bad))
        stats = {}
        assert cio.recover(str(tmp_path), quarantine=False,
                           stats=stats) == good
        # nothing was renamed, so nothing may be counted as quarantined
        assert stats["ckpt_corrupt_quarantined"] == 0
        assert stats["ckpt_recover_fallbacks"] == 1
        assert os.path.exists(bad)

    def test_empty_dir(self, tmp_path):
        assert cio.recover(str(tmp_path)) is None
        assert cio.recover(str(tmp_path / "nope")) is None


class TestDiskChaos:
    def teardown_method(self):
        chaos_mod.uninstall()

    def test_spec_parses_disk_fields(self):
        sched = parse_spec(
            "seed=3;disk:torn_rate=0.2,flip_rate=0.1,enospc_rate=0.05")
        cfg = sched.endpoints["disk"]
        assert (cfg.torn_rate, cfg.flip_rate, cfg.enospc_rate) == (
            0.2, 0.1, 0.05)

    def test_torn_write_leaves_torn_artifact(self, tmp_path):
        good = str(tmp_path / "ckpt_1")
        cio.save(good, user_state(1.0), {"step": 1,
                                         "batches_committed": 1})
        chaos_mod.install(ChaosSchedule(seed=0, endpoints={
            "disk": EndpointChaos(torn_rate=1.0)}))
        torn = str(tmp_path / "ckpt_2")
        with pytest.raises(OSError, match="torn"):
            cio.save(torn, user_state(2.0), {"step": 2,
                                             "batches_committed": 2})
        chaos_mod.uninstall()
        # the torn file sits at the DESTINATION, fails verification, and
        # recovery quarantines it + falls back to the previous good one
        assert os.path.exists(torn)
        assert os.path.getsize(torn) > 0
        with pytest.raises(CheckpointCorruptError):
            cio.verify(torn)
        stats = {}
        assert cio.recover(str(tmp_path), stats=stats) == good
        assert stats["ckpt_corrupt_quarantined"] == 1

    def test_flip_is_silent_until_verify(self, tmp_path):
        chaos_mod.install(ChaosSchedule(seed=0, endpoints={
            "disk": EndpointChaos(flip_rate=1.0)}))
        path = str(tmp_path / "ckpt_1")
        cio.save(path, user_state(), {"step": 1,
                                      "batches_committed": 1})  # no raise
        chaos_mod.uninstall()
        with pytest.raises(CheckpointCorruptError):
            cio.verify(path)

    def test_enospc_raises_fatal_errno(self, tmp_path):
        import errno

        chaos_mod.install(ChaosSchedule(seed=0, endpoints={
            "disk": EndpointChaos(enospc_rate=1.0)}))
        with pytest.raises(OSError) as ei:
            cio.save(str(tmp_path / "ckpt_1"), user_state(),
                     {"step": 1, "batches_committed": 1})
        assert ei.value.errno == errno.ENOSPC

    def test_deterministic_fault_sequence(self):
        def run():
            sched = ChaosSchedule(seed=7, endpoints={
                "disk": EndpointChaos(torn_rate=0.3, flip_rate=0.3,
                                      enospc_rate=0.2)})
            out = []
            for i in range(30):
                try:
                    d = chaos_mod.disk_fault(f"disk:ckpt_{i}", "save",
                                             schedule=sched)
                    out.append(d.fault if d else None)
                except OSError:
                    out.append("enospc")
            return out

        a, b = run(), run()
        assert a == b
        assert "torn" in a and "flip" in a and "enospc" in a


class TestAsyncCheckpointerRobustness:
    def teardown_method(self):
        chaos_mod.uninstall()

    def test_stalled_write_shutdown_returns_within_timeout(self,
                                                           tmp_path):
        """A wedged write (chaos blackhole = stalled NFS) must not hang
        shutdown(): the no-progress watchdog abandons it within the
        stall timeout and surfaces a CheckpointStallError."""
        chaos_mod.install(ChaosSchedule(seed=0, endpoints={
            "disk": EndpointChaos(blackhole_rate=1.0,
                                  blackhole_ms=8_000.0)}))
        ck = AsyncCheckpointer(stall_timeout_sec=0.5)
        ck.save_async(str(tmp_path / "ckpt_1"), {"w": jnp.zeros(4)})
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="previous async"):
            ck.shutdown()
        elapsed = time.monotonic() - t0
        assert elapsed < 4.0, f"shutdown hung {elapsed:.1f}s"
        assert ck.metrics()["ckpt_save_stalls"] == 1
        assert "no progress" in (ck.last_error() or "")

    def test_enospc_fatal_reported_and_reraised(self, tmp_path):
        chaos_mod.install(ChaosSchedule(seed=0, endpoints={
            "disk": EndpointChaos(enospc_rate=1.0)}))
        ck = AsyncCheckpointer()
        try:
            fut = ck.save_async(str(tmp_path / "ckpt_1"),
                                {"w": jnp.zeros(4)})
            with pytest.raises(OSError):
                fut.result(timeout=30)
            mx = ck.metrics()
            assert mx["ckpt_save_errors"] == 1
            assert mx["ckpt_save_fatal"] == 1
            assert "space" in (ck.last_error() or "").lower()
            chaos_mod.uninstall()
            # the latched error still re-raises on the next call
            with pytest.raises(RuntimeError, match="previous async"):
                ck.save_async(str(tmp_path / "ckpt_2"),
                              {"w": jnp.zeros(4)})
        finally:
            ck.shutdown()

    def test_transient_eio_is_not_fatal(self, tmp_path):
        chaos_mod.install(ChaosSchedule(seed=0, endpoints={
            "disk": EndpointChaos(reset_rate=1.0, max_faults=1)}))
        ck = AsyncCheckpointer()
        try:
            fut = ck.save_async(str(tmp_path / "ckpt_1"),
                                {"w": jnp.zeros(4)})
            with pytest.raises(OSError):
                fut.result(timeout=30)
            mx = ck.metrics()
            assert mx["ckpt_save_errors"] == 1
            assert mx["ckpt_save_fatal"] == 0
        finally:
            chaos_mod.uninstall()
            try:
                ck.shutdown()
            except RuntimeError:
                pass

    def test_prune_never_deletes_newest_verified(self, tmp_path):
        """keep=2 with two newer CORRUPT files: retention must protect
        the newest checkpoint that verifies — deleting the last good
        snapshot because garbage outranks it would be data loss."""
        # two corrupt "newer" files that were never valid
        (tmp_path / "ckpt_8").write_bytes(b"TFTCKPT2garbage")
        (tmp_path / "ckpt_9").write_bytes(b"\x00" * 64)
        ck = AsyncCheckpointer(keep=2)
        try:
            for step in (1, 2, 3):
                ck.save_async(str(tmp_path / f"ckpt_{step}"),
                              {"w": jnp.full(2, float(step))},
                              {"step": step, "batches_committed": step})
            ck.wait()
        finally:
            ck.shutdown()
        # ckpt_3 is the newest VERIFIED file and must survive, even
        # though 8 and 9 occupy the keep window
        assert os.path.exists(tmp_path / "ckpt_3")
        assert cio.verify(str(tmp_path / "ckpt_3"))["step"] == 3
        assert not os.path.exists(tmp_path / "ckpt_1")
        assert not os.path.exists(tmp_path / "ckpt_2")
        # and recovery lands on it
        assert cio.recover(str(tmp_path)) == str(tmp_path / "ckpt_3")


class _StateHolder:
    """Mutable user-state cell wired into a mocked-quorum Manager."""

    def __init__(self, w):
        self.state = {"w": w}

    def load(self, s):
        self.state = s

    def dump(self):
        return self.state

    def w_bytes(self):
        return np.asarray(self.state["w"]).tobytes()


class TestManagerDurable:
    def _happy(self, holder):
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = True
        return make_manager(client, load_state_dict=holder.load,
                            state_dict=holder.dump)

    def test_save_durable_stamps_commit_meta(self, tmp_path):
        holder = _StateHolder(np.arange(16, dtype=np.float32))
        m = self._happy(holder)
        ck = AsyncCheckpointer()
        try:
            m.step()
            assert m.should_commit()
            fut = m.save_durable(ck, str(tmp_path))
            assert fut is not None
            path = fut.result(timeout=30)
            head = cio.read_meta(path)
            assert head["step"] == 1
            assert head["committed"] is True
            assert head["quorum_id"] == 1
            assert head["replica_id"] == "testgroup"
            assert head["participants"] == 2
            assert cio.verify(path)["step"] == 1
            mx = m.metrics()
            assert mx["ckpt_save_count"] == 1
            assert mx["ckpt_save_fatal"] == 0
        finally:
            ck.shutdown()
            m.shutdown()

    def test_refuses_errored_and_uncommitted_state(self, tmp_path):
        holder = _StateHolder(np.zeros(4, np.float32))
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = False  # vote aborts
        m = make_manager(client, load_state_dict=holder.load,
                         state_dict=holder.dump)
        ck = AsyncCheckpointer()
        try:
            m.step()
            m.report_error(RuntimeError("boom"))
            assert m.save_durable(ck, str(tmp_path)) is None  # errored
            assert not m.should_commit()
            assert m.save_durable(ck, str(tmp_path)) is None  # aborted
            mx = m.metrics()
            assert mx["ckpt_save_skipped"] == 2
            assert "ckpt_skip" in [e["event"] for e in m.history()]
            assert os.listdir(tmp_path) == []
        finally:
            ck.shutdown()
            m.shutdown()

    def test_refuses_mid_heal_snapshot(self, tmp_path):
        holder = _StateHolder(np.zeros(4, np.float32))
        m = self._happy(holder)
        ck = AsyncCheckpointer()
        try:
            with m._metrics_lock:  # unit shortcut: flag a staged heal
                m._healing = True
            assert m.save_durable(ck, str(tmp_path)) is None
            assert m.metrics()["ckpt_save_skipped"] == 1
            assert os.listdir(tmp_path) == []
        finally:
            ck.shutdown()
            m.shutdown()


class TestManagerColdStart:
    def test_cold_start_restores_newest_verified(self, tmp_path):
        w5 = np.arange(32, dtype=np.float32)
        cio.save(str(tmp_path / "ckpt_5"), {"w": w5},
                 {"step": 5, "batches_committed": 10},
                 meta={"quorum_id": 2, "replica_id": "old"})
        bad = str(tmp_path / "ckpt_9")
        cio.save(bad, {"w": np.zeros(32, np.float32)},
                 {"step": 9, "batches_committed": 18})
        _flip_at(bad, _first_leaf_offset(bad))

        holder = _StateHolder(np.zeros(32, np.float32))
        client = MagicMock()
        m = make_manager(client, load_state_dict=holder.load,
                         state_dict=holder.dump)
        try:
            path = m.cold_start(str(tmp_path))
            assert path == str(tmp_path / "ckpt_5")
            assert m.current_step() == 5
            assert m.batches_committed() == 10
            assert holder.w_bytes() == w5.tobytes()
            mx = m.metrics()
            assert mx["ckpt_cold_starts"] == 1
            assert mx["ckpt_corrupt_quarantined"] == 1
            assert mx["ckpt_recover_fallbacks"] == 1
            events = [e for e in m.history() if e["event"] == "cold_start"]
            assert events and events[-1]["recovered"] is True
        finally:
            m.shutdown()

    def test_cold_start_empty_dir_is_fresh_start(self, tmp_path):
        holder = _StateHolder(np.zeros(4, np.float32))
        client = MagicMock()
        m = make_manager(client, load_state_dict=holder.load,
                         state_dict=holder.dump)
        try:
            assert m.cold_start(str(tmp_path)) is None
            assert m.current_step() == 0
            assert m.metrics()["ckpt_cold_starts"] == 0
        finally:
            m.shutdown()


class TestColdStartConvergence:
    """THE acceptance: two groups cold-started from DIFFERENT on-disk
    steps (correlated failure with divergent last-good snapshots) end
    bitwise identical at the newest committed step, via the existing
    max_step heal path — no extra reconciliation protocol."""

    def test_divergent_cold_starts_converge_bitwise(self, tmp_path):
        from torchft_tpu.checkpointing import CheckpointServer

        rng = np.random.RandomState(11)
        wA = rng.rand(4096).astype(np.float32)   # newest committed (10)
        wB = rng.rand(4096).astype(np.float32)   # stale (8)
        cio.save(str(tmp_path / "a" / "ckpt_10"), {"w": wA},
                 {"step": 10, "batches_committed": 20},
                 meta={"quorum_id": 4, "replica_id": "gA"})
        cio.save(str(tmp_path / "b" / "ckpt_8"), {"w": wB},
                 {"step": 8, "batches_committed": 16},
                 meta={"quorum_id": 3, "replica_id": "gB"})
        # and a torn newest file in B's dir: recovery must skip it
        torn = tmp_path / "b" / "ckpt_9"
        torn.write_bytes(b"TFTCKPT2\x40\x00\x00\x00partial head junk")

        holderA = _StateHolder(np.zeros(4096, np.float32))
        holderB = _StateHolder(np.zeros(4096, np.float32))

        # group A: cold-starts at 10, participates, serves heals
        cellA = {}
        srvA = CheckpointServer(
            lambda: cellA["m"]._manager_state_dict(),
            bind_host="127.0.0.1")
        clientA = MagicMock()
        clientA.quorum.return_value = quorum_result(
            quorum_id=5, max_step=11, max_rank=0, max_world_size=2,
            replica_rank=0, replica_world_size=2)
        clientA.should_commit.return_value = True
        mA = make_manager(clientA, load_state_dict=holderA.load,
                          state_dict=holderA.dump, min_replica_size=1,
                          checkpoint_transport=srvA)
        cellA["m"] = mA

        # group B: cold-starts at 8, must heal from A
        clientB = MagicMock()
        clientB.quorum.return_value = quorum_result(
            quorum_id=5, max_step=11, max_rank=None, max_world_size=1,
            replica_rank=1, replica_world_size=2, heal=True,
            recover_manager_address="managerA")
        clientB.should_commit.return_value = True
        mB = make_manager(clientB, load_state_dict=holderB.load,
                          state_dict=holderB.dump, min_replica_size=1)

        def make_client(addr, **kwargs):
            mc = MagicMock()
            mc.checkpoint_address.side_effect = (
                lambda *a, **k: srvA.address())
            return mc

        try:
            assert mA.cold_start(str(tmp_path / "a")) is not None
            assert mA.current_step() == 10
            statsB = mB.cold_start(str(tmp_path / "b"))
            assert statsB == str(tmp_path / "b" / "ckpt_8")
            assert mB.current_step() == 8
            assert mB.metrics()["ckpt_corrupt_quarantined"] == 1
            # the two groups rejoin the quorum at divergent steps
            assert holderA.w_bytes() != holderB.w_bytes()

            with patch("torchft_tpu.manager.ManagerClient",
                       side_effect=make_client):
                mA.step()     # advances to 11, opens the serve window
                mB.step()     # quorum says: heal from A at max_step 11
                assert mB.should_commit()   # heal fetched + applied
                assert mA.should_commit()
        finally:
            mB.shutdown()
            mA.shutdown()

        # converged: bitwise identical at the newest committed step
        assert mA.current_step() == mB.current_step() == 11
        assert holderA.w_bytes() == holderB.w_bytes()
        assert holderB.w_bytes() == wA.tobytes()
        assert mB.metrics()["heal_count"] == 1
        assert mB.metrics()["heal_bytes_total"] > 0


@pytest.mark.cold_start
@pytest.mark.slow
@pytest.mark.nightly
class TestColdStartSoak:
    """Seeded kill-all → cold-restart soak (``scripts/test.sh
    cold-start``): every round a 2-group job checkpoints under disk
    chaos (torn writes, silent bit-flips, ENOSPC), then the whole fleet
    "dies" and cold-restarts from disk. Invariants per round: recovery
    never loads unverified bytes (every recovered file re-verifies and
    matches the state recorded at save time bitwise), and never
    regresses past the newest CLEAN save (regression is bounded by the
    checkpoint cadence around injected faults)."""

    ROUNDS = 4
    STEPS = 18
    CADENCE = 3

    def test_kill_all_cold_restart_rounds(self, tmp_path):
        for rnd in range(self.ROUNDS):
            self._one_round(rnd, tmp_path / f"r{rnd}")

    def _one_round(self, rnd, root):
        rng = np.random.RandomState(100 + rnd)
        sched = ChaosSchedule(seed=200 + rnd, endpoints={
            "disk": EndpointChaos(torn_rate=0.2, flip_rate=0.15,
                                  enospc_rate=0.08)})
        chaos_mod.install(sched)
        groups = {g: {"w": rng.rand(512).astype(np.float32)}
                  for g in (0, 1)}
        recorded = {g: {} for g in groups}   # step -> state bytes
        clean = {g: [] for g in groups}      # steps with fault-free saves
        try:
            for step in range(1, self.STEPS + 1):
                for g, state in groups.items():
                    # deterministic "training": the committed update
                    state["w"] = state["w"] * 1.01 + g
                    if step % self.CADENCE != 0:
                        continue
                    recorded[g][step] = state["w"].tobytes()
                    n_before = len(sched.trace())
                    try:
                        cio.save(str(root / str(g) / f"ckpt_{step}"),
                                 {"w": state["w"]},
                                 {"step": step,
                                  "batches_committed": 2 * step})
                    except OSError:
                        continue  # torn / ENOSPC / EIO: save failed
                    faults = [d.fault for d in
                              sched.trace()[n_before:] if d.fault]
                    if not faults:
                        clean[g].append(step)
        finally:
            chaos_mod.uninstall()

        # ---- kill-all: every group is gone; cold-restart from disk ----
        for g in groups:
            stats = {}
            path = cio.recover(str(root / str(g)), stats=stats)
            assert clean[g], "soak produced no clean save; relax rates"
            assert path is not None, (
                f"round {rnd} group {g}: no recovery despite clean "
                f"saves at {clean[g]}")
            # never an unverified load: the file re-verifies...
            head = cio.verify(path)
            user, mgr = cio.load(path, target={
                "w": np.zeros(512, np.float32)}, device_put=False)
            step = mgr["step"]
            assert head["committed"] is True
            # ...and the loaded bytes are exactly what was recorded at
            # that step (a silently-flipped file can never get here)
            assert user["w"].tobytes() == recorded[g][step], (
                f"round {rnd} group {g}: recovered state at step {step} "
                "does not match the state saved there")
            # bounded regression: at least the newest clean save
            assert step >= max(clean[g]), (
                f"round {rnd} group {g}: recovered step {step} < newest "
                f"clean save {max(clean[g])}")
