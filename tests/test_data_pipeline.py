"""Storage-backed stateful input pipeline (VERDICT r2 #7).

The reference delegates storage to torchvision/torchdata and documents a
lossy-rejoin contract for the sampler (/root/reference/torchft/data.py:33-36)
with exact resume via StatefulDataLoader (train_ddp.py:53-57). Here the
memmap datasets + StatefulLoader play both roles; these tests pin:
round-tripping through disk, O(batch) gathering, exact-position resume,
disjoint cross-group sharding, and the lossy-rejoin story end to end.
"""

import numpy as np
import pytest

from torchft_tpu.data import (
    DistributedSampler,
    MemmapDataset,
    StatefulLoader,
    TokenFileDataset,
)


@pytest.fixture
def corpus(tmp_path):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    y = rng.integers(0, 10, size=(256,)).astype(np.int32)
    return MemmapDataset.write(str(tmp_path / "ds"), {"x": x, "y": y}), x, y


class TestMemmapDataset:
    def test_round_trip_and_gather(self, corpus):
        ds, x, y = corpus
        assert len(ds) == 256
        idx = np.array([3, 200, 7])
        batch = ds[idx]
        np.testing.assert_array_equal(batch["x"], x[idx])
        np.testing.assert_array_equal(batch["y"], y[idx])
        # Gathered batches are real arrays (writable), not memmap views.
        assert isinstance(batch["x"], np.ndarray)
        assert not isinstance(batch["x"], np.memmap)

    def test_fields_are_memmapped(self, corpus):
        ds, _, _ = corpus
        assert all(isinstance(a, np.memmap) for a in ds.arrays.values())

    def test_ragged_fields_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="rows"):
            MemmapDataset.write(str(tmp_path / "bad"),
                                {"a": np.ones(4), "b": np.ones(5)})


class TestTokenFileDataset:
    def test_windows(self, tmp_path):
        toks = np.arange(100, dtype=np.uint16)
        path = str(tmp_path / "tokens.npy")
        TokenFileDataset.write(path, toks)
        ds = TokenFileDataset(path, seq_len=16)
        assert len(ds) == 6  # 100 // 16
        batch = ds[np.array([0, 5])]
        assert batch["tokens"].dtype == np.int32
        np.testing.assert_array_equal(batch["tokens"][0], np.arange(16))
        np.testing.assert_array_equal(batch["tokens"][1],
                                      np.arange(80, 96))


def collect(loader, n):
    return [next(loader) for _ in range(n)]


class TestStatefulLoader:
    def make(self, corpus, group=0, num_groups=2, prefetch=2):
        ds, _, _ = corpus
        sampler = DistributedSampler(len(ds), group, num_groups,
                                     batch_size=8, seed=3)
        return StatefulLoader(ds, sampler, prefetch=prefetch)

    @pytest.mark.parametrize("prefetch", [0, 3])
    def test_exact_resume(self, corpus, prefetch):
        """state_dict after batch k resumes the stream at batch k+1,
        regardless of how far the prefetcher has read ahead."""
        a = self.make(corpus, prefetch=prefetch)
        seen = collect(a, 5)
        state = a.state_dict()
        cont = collect(a, 4)
        a.shutdown()

        b = self.make(corpus, prefetch=prefetch)
        b.load_state_dict(state)
        resumed = collect(b, 4)
        b.shutdown()
        for p, q in zip(cont, resumed):
            np.testing.assert_array_equal(p["x"], q["x"])
        # And the pre-checkpoint stream differs from the post (sanity).
        assert not np.array_equal(seen[0]["x"], cont[0]["x"])

    def test_epochs_auto_advance_with_reshuffle(self, corpus):
        ds, _, _ = corpus
        sampler = DistributedSampler(len(ds), 0, 1, batch_size=32, seed=0)
        loader = StatefulLoader(ds, sampler, prefetch=0)
        epoch0 = np.concatenate(
            [b["y"] for b in collect(loader, len(sampler))])
        epoch1 = np.concatenate(
            [b["y"] for b in collect(loader, len(sampler))])
        assert sorted(epoch0.tolist()) == sorted(epoch1.tolist())
        assert not np.array_equal(epoch0, epoch1)  # reshuffled
        loader.shutdown()

    def test_groups_shard_disjointly(self, corpus):
        ds, x, _ = corpus
        rows = []
        for g in range(2):
            loader = self.make(corpus, group=g)
            got = np.concatenate([b["x"] for b in collect(loader, 4)])
            loader.shutdown()
            rows.append({tuple(r) for r in got})
        assert not rows[0] & rows[1]

    def test_lossy_rejoin_story(self, corpus):
        """The end-to-end contract: a group checkpoints at batch 4, keeps
        consuming to batch 9, dies, restarts from the checkpoint — the
        resumed stream REPLAYS batches 5..9 exactly (lossy: those samples
        are consumed twice), then continues deterministically."""
        a = self.make(corpus)
        collect(a, 4)
        ckpt = a.state_dict()         # durable checkpoint at batch 4
        tail_before_death = collect(a, 5)  # batches 5..9, then the crash
        a.shutdown()

        b = self.make(corpus)         # fresh process
        b.load_state_dict(ckpt)
        replayed = collect(b, 5)
        b.shutdown()
        for p, q in zip(tail_before_death, replayed):
            np.testing.assert_array_equal(p["x"], q["x"])

    def test_empty_shard_rejected(self, corpus):
        ds, _, _ = corpus
        sampler = DistributedSampler(4, 0, 2, batch_size=8)  # 2 rows < 8
        with pytest.raises(ValueError, match="no batches"):
            StatefulLoader(ds, sampler)
