"""Storage-backed stateful input pipeline (VERDICT r2 #7).

The reference delegates storage to torchvision/torchdata and documents a
lossy-rejoin contract for the sampler (/root/reference/torchft/data.py:33-36)
with exact resume via StatefulDataLoader (train_ddp.py:53-57). Here the
memmap datasets + StatefulLoader play both roles; these tests pin:
round-tripping through disk, O(batch) gathering, exact-position resume,
disjoint cross-group sharding, and the lossy-rejoin story end to end.
"""

import time

import numpy as np
import pytest

from torchft_tpu.data import (
    DistributedSampler,
    MemmapDataset,
    StatefulLoader,
    TokenFileDataset,
)


@pytest.fixture
def corpus(tmp_path):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    y = rng.integers(0, 10, size=(256,)).astype(np.int32)
    return MemmapDataset.write(str(tmp_path / "ds"), {"x": x, "y": y}), x, y


class TestMemmapDataset:
    def test_round_trip_and_gather(self, corpus):
        ds, x, y = corpus
        assert len(ds) == 256
        idx = np.array([3, 200, 7])
        batch = ds[idx]
        np.testing.assert_array_equal(batch["x"], x[idx])
        np.testing.assert_array_equal(batch["y"], y[idx])
        # Gathered batches are real arrays (writable), not memmap views.
        assert isinstance(batch["x"], np.ndarray)
        assert not isinstance(batch["x"], np.memmap)

    def test_fields_are_memmapped(self, corpus):
        ds, _, _ = corpus
        assert all(isinstance(a, np.memmap) for a in ds.arrays.values())

    def test_ragged_fields_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="rows"):
            MemmapDataset.write(str(tmp_path / "bad"),
                                {"a": np.ones(4), "b": np.ones(5)})


class TestTokenFileDataset:
    def test_windows(self, tmp_path):
        toks = np.arange(100, dtype=np.uint16)
        path = str(tmp_path / "tokens.npy")
        TokenFileDataset.write(path, toks)
        ds = TokenFileDataset(path, seq_len=16)
        assert len(ds) == 6  # 100 // 16
        batch = ds[np.array([0, 5])]
        assert batch["tokens"].dtype == np.int32
        np.testing.assert_array_equal(batch["tokens"][0], np.arange(16))
        np.testing.assert_array_equal(batch["tokens"][1],
                                      np.arange(80, 96))


def collect(loader, n):
    return [next(loader) for _ in range(n)]


class TestStatefulLoader:
    def make(self, corpus, group=0, num_groups=2, prefetch=2):
        ds, _, _ = corpus
        sampler = DistributedSampler(len(ds), group, num_groups,
                                     batch_size=8, seed=3)
        return StatefulLoader(ds, sampler, prefetch=prefetch)

    @pytest.mark.parametrize("prefetch", [0, 3])
    def test_exact_resume(self, corpus, prefetch):
        """state_dict after batch k resumes the stream at batch k+1,
        regardless of how far the prefetcher has read ahead."""
        a = self.make(corpus, prefetch=prefetch)
        seen = collect(a, 5)
        state = a.state_dict()
        cont = collect(a, 4)
        a.shutdown()

        b = self.make(corpus, prefetch=prefetch)
        b.load_state_dict(state)
        resumed = collect(b, 4)
        b.shutdown()
        for p, q in zip(cont, resumed):
            np.testing.assert_array_equal(p["x"], q["x"])
        # And the pre-checkpoint stream differs from the post (sanity).
        assert not np.array_equal(seen[0]["x"], cont[0]["x"])

    def test_epochs_auto_advance_with_reshuffle(self, corpus):
        ds, _, _ = corpus
        sampler = DistributedSampler(len(ds), 0, 1, batch_size=32, seed=0)
        loader = StatefulLoader(ds, sampler, prefetch=0)
        epoch0 = np.concatenate(
            [b["y"] for b in collect(loader, len(sampler))])
        epoch1 = np.concatenate(
            [b["y"] for b in collect(loader, len(sampler))])
        assert sorted(epoch0.tolist()) == sorted(epoch1.tolist())
        assert not np.array_equal(epoch0, epoch1)  # reshuffled
        loader.shutdown()

    def test_groups_shard_disjointly(self, corpus):
        ds, x, _ = corpus
        rows = []
        for g in range(2):
            loader = self.make(corpus, group=g)
            got = np.concatenate([b["x"] for b in collect(loader, 4)])
            loader.shutdown()
            rows.append({tuple(r) for r in got})
        assert not rows[0] & rows[1]

    def test_lossy_rejoin_story(self, corpus):
        """The end-to-end contract: a group checkpoints at batch 4, keeps
        consuming to batch 9, dies, restarts from the checkpoint — the
        resumed stream REPLAYS batches 5..9 exactly (lossy: those samples
        are consumed twice), then continues deterministically."""
        a = self.make(corpus)
        collect(a, 4)
        ckpt = a.state_dict()         # durable checkpoint at batch 4
        tail_before_death = collect(a, 5)  # batches 5..9, then the crash
        a.shutdown()

        b = self.make(corpus)         # fresh process
        b.load_state_dict(ckpt)
        replayed = collect(b, 5)
        b.shutdown()
        for p, q in zip(tail_before_death, replayed):
            np.testing.assert_array_equal(p["x"], q["x"])

    def test_empty_shard_rejected(self, corpus):
        ds, _, _ = corpus
        sampler = DistributedSampler(4, 0, 2, batch_size=8)  # 2 rows < 8
        with pytest.raises(ValueError, match="no batches"):
            StatefulLoader(ds, sampler)


class _FakeFTManager:
    """Scripted (batches_committed, participant_rank) source for
    ElasticSampler coverage tests."""

    def __init__(self, rank):
        self.bc = 0
        self.rank = rank

    def batches_committed(self):
        return self.bc

    def participant_rank(self):
        return self.rank


class TestElasticSampler:
    def _samplers(self, world, n=64, b=4, seed=3):
        from torchft_tpu.data import ElasticSampler
        mgrs = [_FakeFTManager(r) for r in range(world)]
        return mgrs, [ElasticSampler(n, m, batch_size=b, seed=seed)
                      for m in mgrs]

    def test_steady_state_partition(self):
        """World=3 lockstep: per step the groups draw disjoint slots; over
        an epoch the union covers the permutation exactly once."""
        world, n, b = 3, 60, 4
        mgrs, samplers = self._samplers(world, n=n, b=b)
        batches_per_epoch = n // b
        drawn = []
        steps = batches_per_epoch // world
        for _ in range(steps):
            for s in samplers:
                drawn.append(s.next_indices())
            for m in mgrs:
                m.bc += world  # commit
        flat = np.concatenate(drawn)
        assert len(flat) == steps * world * b
        assert len(np.unique(flat)) == len(flat)  # no duplicates

    def test_abort_redraws_same_slots(self):
        mgrs, samplers = self._samplers(2)
        first = [s.next_indices() for s in samplers]
        # no commit -> bc unchanged -> identical redraw
        again = [s.next_indices() for s in samplers]
        for a, c in zip(first, again):
            np.testing.assert_array_equal(a, c)

    def test_prefers_atomic_slot_snapshot(self):
        """With a real Manager the sampler must read the slot through the
        participant_slot() atomic snapshot, never the two-call sequence a
        concurrent quorum could tear (torn pair = wrong slot drawn)."""
        from torchft_tpu.data import ElasticSampler

        class SnapshotManager(_FakeFTManager):
            def __init__(self):
                super().__init__(rank=1)
                self.snapshot_calls = 0

            def participant_slot(self):
                self.snapshot_calls += 1
                return self.rank, self.bc

            def participant_rank(self):  # must NOT be used
                raise AssertionError("torn two-read path used")

            def batches_committed(self):
                raise AssertionError("torn two-read path used")

        m = SnapshotManager()
        m.bc = 10
        s = ElasticSampler(64, m, batch_size=4, seed=0)
        np.testing.assert_array_equal(
            s.next_indices(), s.indices_for_slot(11))
        assert m.snapshot_calls == 1

    def test_membership_shrink_repartitions(self):
        """3 -> 2 groups: after the survivors' ranks and bc update, the
        stream continues with no gaps or duplicates."""
        world, n, b = 3, 120, 2
        mgrs, samplers = self._samplers(world, n=n, b=b)
        slots = []

        def draw(live):
            for i in live:
                idx = samplers[i].next_indices()
                m = mgrs[i]
                slots.append(m.bc + m.rank)
            for i in live:
                mgrs[i].bc += len(live)

        draw([0, 1, 2])
        draw([0, 1, 2])
        # group 2 dies; survivors keep ranks 0,1 in the new quorum
        draw([0, 1])
        draw([0, 1])
        assert sorted(slots) == list(range(len(slots)))  # contiguous, unique

    def test_healing_group_draws_throwaway(self):
        from torchft_tpu.data import ElasticSampler
        m = _FakeFTManager(rank=None)
        s = ElasticSampler(16, m, batch_size=4)
        idx = s.next_indices()  # must not raise; rank treated as 0
        assert idx.shape == (4,)

    def test_shuffle_deterministic_across_instances(self):
        from torchft_tpu.data import ElasticSampler
        a = ElasticSampler(32, _FakeFTManager(0), batch_size=4, seed=9)
        b = ElasticSampler(32, _FakeFTManager(0), batch_size=4, seed=9)
        np.testing.assert_array_equal(a.next_indices(), b.next_indices())

    def test_epoch_wrap_reshuffles(self):
        from torchft_tpu.data import ElasticSampler
        m = _FakeFTManager(0)
        s = ElasticSampler(8, m, batch_size=4, seed=1)
        e0 = [s.next_indices().copy()]
        m.bc += 1
        e0.append(s.next_indices().copy())
        m.bc += 1  # epoch 1 begins
        e1 = [s.next_indices().copy()]
        m.bc += 1
        e1.append(s.next_indices().copy())
        cover0 = np.sort(np.concatenate(e0))
        cover1 = np.sort(np.concatenate(e1))
        np.testing.assert_array_equal(cover0, np.arange(8))
        np.testing.assert_array_equal(cover1, np.arange(8))
        assert not all(
            np.array_equal(x, y) for x, y in zip(e0, e1))  # reshuffled


class TestElasticLoader:
    """ElasticSampler x storage tier (round-4 verdict missing #4)."""

    def _mk(self, corpus, rank=0, prefetch=2):
        from torchft_tpu.data import ElasticLoader, ElasticSampler
        ds, x, y = corpus
        m = _FakeFTManager(rank)
        s = ElasticSampler(len(ds), m, batch_size=8, seed=5)
        return ElasticLoader(ds, s, prefetch=prefetch), s, m, x, y

    def test_draws_match_slot_indices(self, corpus):
        loader, s, m, x, y = self._mk(corpus)
        try:
            for _ in range(4):
                idx = s.next_indices()
                batch = loader()
                np.testing.assert_array_equal(batch["x"], x[idx])
                np.testing.assert_array_equal(batch["y"], y[idx])
                m.bc += 1  # commit
        finally:
            loader.shutdown()

    def test_prefetch_hits_on_committed_stream(self, corpus):
        loader, s, m, x, y = self._mk(corpus)
        try:
            loader()           # cold draw: miss, schedules bc+1, bc+2
            deadline = time.monotonic() + 10
            for _ in range(6):
                m.bc += 1
                # Let the background read land; a miss is CORRECT but we
                # assert the predictor mostly wins on a steady stream.
                while time.monotonic() < deadline:
                    with loader._lock:
                        # Cache keys are (slot, capacity_fraction) since
                        # degraded-mode draws (docs/design/degraded_mode.md).
                        if (m.bc, 1.0) in loader._cache:
                            break
                    time.sleep(0.01)
                batch = loader()
                np.testing.assert_array_equal(
                    batch["x"], x[s.indices_for_slot(m.bc)])
            assert loader.prefetch_hits >= 4, (
                loader.prefetch_hits, loader.prefetch_misses)
        finally:
            loader.shutdown()

    def test_abort_redraw_served_from_cache(self, corpus):
        loader, s, m, x, y = self._mk(corpus)
        try:
            a = loader()
            b = loader()  # same slot (abort: bc unchanged) -> cache hit
            np.testing.assert_array_equal(a["x"], b["x"])
            assert loader.prefetch_hits == 1
        finally:
            loader.shutdown()

    def test_membership_change_still_exact(self, corpus):
        # A rank/participant change invalidates the prediction, never the
        # draw: the slot is recomputed live, at worst costing a sync read.
        loader, s, m, x, y = self._mk(corpus, rank=1)
        try:
            loader()
            m.rank = 0          # membership changed under the loader
            m.bc += 3           # commits advanced unpredictably
            idx = s.next_indices()
            np.testing.assert_array_equal(loader()["x"], x[idx])
        finally:
            loader.shutdown()

    def test_token_file_backend(self, tmp_path):
        from torchft_tpu.data import (ElasticLoader, ElasticSampler,
                                      TokenFileDataset)
        toks = np.arange(16 * 64, dtype=np.int64) % 1000
        TokenFileDataset.write(str(tmp_path / "t.npy"), toks)
        ds = TokenFileDataset(str(tmp_path / "t.npy"), seq_len=16)
        m = _FakeFTManager(0)
        s = ElasticSampler(len(ds), m, batch_size=4, seed=0)
        loader = ElasticLoader(ds, s, prefetch=1)
        try:
            batch = loader()
            assert batch["tokens"].shape == (4, 16)
            rows = s.indices_for_slot(0)
            np.testing.assert_array_equal(
                batch["tokens"][0],
                toks[rows[0] * 16:(rows[0] + 1) * 16].astype(np.int32))
        finally:
            loader.shutdown()


@pytest.mark.integration
class TestElasticSamplerIntegration:
    def test_coverage_survives_death_and_heal(self):
        """Two groups draw from one elastic stream; one dies and a fresh
        incarnation rejoins (batches_committed rides the healed manager
        state). Committed-step slots must stay gap-free, with duplicates
        bounded by the membership changes."""
        import threading
        from torchft_tpu import (ElasticSampler, HostCommunicator,
                                 Lighthouse, Manager)

        total_commits = 14
        kill_after = 4
        n, b = 512, 4
        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1,
                        join_timeout_ms=500, quorum_tick_ms=20)
        records = {"gA": [], "gB": []}
        done = threading.Event()

        def make(gid):
            m = Manager(
                comm=HostCommunicator(timeout_sec=15),
                load_state_dict=lambda s: None, state_dict=lambda: {},
                min_replica_size=1, replica_id=gid,
                lighthouse_addr=lh.address(), rank=0, world_size=1,
                timeout_ms=15_000, quorum_timeout_ms=15_000)
            return m, ElasticSampler(n, m, batch_size=b, seed=5)

        def run_until(m, s, gid, stop_at):
            while m.current_step() < stop_at and not done.is_set():
                m.step()
                idx = s.next_indices()
                slot = (m.batches_committed(),)  # pre-commit snapshot
                m.allreduce({"g": np.ones(2, np.float32)}).result(timeout=30)
                committed = m.should_commit()
                rank = m.participant_rank()
                if committed and rank is not None:
                    records[gid].append(
                        (slot[0] + rank, tuple(np.sort(idx))))

        def survivor():
            m, s = make("gA")
            try:
                run_until(m, s, "gA", total_commits)
            finally:
                done.set()
                m.shutdown()

        def victim():
            m, s = make("gB")
            try:
                run_until(m, s, "gB", kill_after)
            finally:
                m.shutdown()  # dies
            m, s = make("gB")  # fresh incarnation; bc heals from gA
            try:
                run_until(m, s, "gB", total_commits)
            finally:
                m.shutdown()

        ts = [threading.Thread(target=survivor),
              threading.Thread(target=victim)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=180)
        lh.shutdown()
        assert not any(t.is_alive() for t in ts)

        # Slot -> drawn indices; the same slot must always map to the
        # same indices (deterministic shared permutation).
        slot_map = {}
        for gid in records:
            for slot, idx in records[gid]:
                assert slot_map.setdefault(slot, idx) == idx, \
                    f"slot {slot} drew different indices across groups"
        slots = sorted(slot_map)
        assert len(slots) >= total_commits
        assert slots[0] == 0
        # Documented contract: at most one step's slots skipped per
        # membership event. This run has three (initial sync heal, the
        # kill, the rejoin heal) — static sharding would instead lose
        # whole shards for whole epochs.
        gaps = set(range(slots[0], slots[-1] + 1)) - set(slots)
        assert len(gaps) <= 3, f"too many skipped slots: {sorted(gaps)}"
