"""Fleet health plane tests (:mod:`torchft_tpu.fleet`,
docs/design/fleet_health.md).

Tier-1 and native-free via the pure-Python aggregator mirror: the
straggler-score battery (known-skew fleets, single-group no-NaN,
healer/degraded exclusion), slowest-stage attribution, staleness /
farewell pruning, the SLO engine's thresholds + (slo, group, step)
dedup, the frozen ``/fleet/metrics`` exposition names, the dashboard
table, ``scripts/tracefleet.py --fleet`` address resolution over a live
stub, ``scripts/benchdiff.py``'s direction vocabulary and gating, and
the Manager-side halves (digest push deltas, hint consumption, the
SLO-breach flight dump).

The native rounds (4-group piggyback drive with an artificially slowed
group, the Python-vs-C++ aggregator parity check, the churn-coherence
soak) are gated on the toolchain and ride nightly — the C++ unit
matrix itself lives in ``_core/core_test.cc``.
"""

import json
import os
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from unittest.mock import MagicMock

import numpy as np
import pytest

import conftest
from torchft_tpu import fleet, tracing
from torchft_tpu._native import QuorumResult
from torchft_tpu.communicator import DummyCommunicator
from torchft_tpu.fleet import (FleetAggregator, SLOConfig, SLOEngine,
                               StepDigest, attribute_stage,
                               format_fleet_table, resolve_trace_addrs,
                               robust_zscores, status_prometheus)
from torchft_tpu.manager import Manager

pytestmark = pytest.mark.fleet

requires_native = conftest.requires_native()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def mk_digest(rid, wall, step=5, fetch=0.0, ring=0.0, put=0.0,
              vote=0.0, healing=False, capacity=1.0, **kw):
    return StepDigest(replica_id=rid, step=step, step_wall_ms=wall,
                      fetch_ms=fetch, ring_ms=ring, put_ms=put,
                      vote_ms=vote, healing=healing,
                      capacity_fraction=capacity, **kw)


def hint(fleet_p95_ms=0.0, straggler_score=0.0, fleet_groups=0,
         straggler_stage="", straggler_id="", slo_breach=""):
    """A QuorumResult carrying only the fleet-hint fields the
    consumption path reads (the rest is a minimal valid quorum)."""
    return QuorumResult(
        quorum_id=1, recover_manager_address="m:1", store_address="",
        max_step=1, max_rank=0, max_world_size=1, replica_rank=0,
        replica_world_size=1, heal=False,
        fleet_p95_ms=fleet_p95_ms, straggler_score=straggler_score,
        fleet_groups=fleet_groups, straggler_stage=straggler_stage,
        straggler_id=straggler_id, slo_breach=slo_breach)


def make_manager(client=None, replica_id="fleet0", **kw):
    if client is None:
        client = MagicMock()
        client.quorum.return_value = hint()
        client.should_commit.return_value = True
    return Manager(
        comm=DummyCommunicator(),
        load_state_dict=MagicMock(),
        state_dict=lambda: {"w": np.arange(8, dtype=np.float32)},
        min_replica_size=1,
        use_async_quorum=False,
        rank=0, world_size=1,
        replica_id=replica_id,
        _manager_client=client,
        **kw,
    )


# ------------------------------------------------------- straggler math


class TestRobustZ:
    def test_empty_and_single(self):
        assert robust_zscores([]) == []
        # A single-group fleet has no dispersion: score 0.0, never NaN.
        assert robust_zscores([123.4]) == [0.0]

    def test_uniform_fleet_all_zero(self):
        scores = robust_zscores([100.0] * 8)
        assert scores == [0.0] * 8
        assert all(np.isfinite(scores))

    def test_known_skew_fleet_ranks_the_outlier(self):
        walls = [100.0, 101.0, 99.0, 100.5, 3000.0]
        scores = robust_zscores(walls)
        assert all(np.isfinite(scores))
        assert max(scores) == scores[4]
        assert scores[4] > 10.0  # wildly out vs a tight baseline
        assert all(abs(s) < 3.0 for s in scores[:4])

    def test_zero_mad_with_one_outlier_stays_finite(self):
        # Majority identical -> MAD 0 -> guarded to all-zero, not inf.
        assert robust_zscores([100.0, 100.0, 100.0, 900.0]) == [0.0] * 4

    def test_symmetric_negative_scores(self):
        scores = robust_zscores([50.0, 100.0, 150.0])
        assert scores[0] < 0 < scores[2]
        assert scores[1] == 0.0


class TestAttribution:
    MED = {"fetch": 10.0, "ring": 10.0, "put": 10.0, "vote": 10.0}

    def test_largest_excess_wins(self):
        stage = attribute_stage(
            {"fetch": 12.0, "ring": 500.0, "put": 11.0, "vote": 9.0},
            self.MED)
        assert stage == "ring"

    def test_tie_breaks_in_protocol_order(self):
        stage = attribute_stage(
            {"fetch": 50.0, "ring": 50.0, "put": 10.0, "vote": 10.0},
            self.MED)
        assert stage == "fetch"  # DIGEST_STAGES order wins ties

    def test_all_under_median_falls_back_to_own_biggest(self):
        stage = attribute_stage(
            {"fetch": 1.0, "ring": 5.0, "put": 2.0, "vote": 1.0},
            self.MED)
        assert stage == "ring"

    def test_all_zero_stages_unattributed(self):
        assert attribute_stage(
            {"fetch": 0.0, "ring": 0.0, "put": 0.0, "vote": 0.0},
            self.MED) == ""


class TestAggregator:
    def test_known_skew_fleet_ranking_and_attribution(self):
        agg = FleetAggregator()
        now = 1_000_000
        for i in range(3):
            agg.ingest(mk_digest(f"g{i}", 100.0 + i, fetch=25.0,
                                 ring=10.0, put=5.0, vote=2.0),
                       now_ms=now)
        agg.ingest(mk_digest("g3", 3000.0, fetch=25.0, ring=2500.0,
                             put=5.0, vote=2.0), now_ms=now)
        st = agg.aggregate(now_ms=now)
        assert st["fleet"]["groups"] == 4
        assert st["fleet"]["baseline_groups"] == 4
        assert st["fleet"]["p95_ms"] == 3000.0
        assert st["fleet"]["max_ms"] == 3000.0
        assert st["straggler"]["replica_id"] == "g3"
        assert st["straggler"]["stage"] == "ring"
        assert st["straggler"]["score"] > 10.0
        # worst-first ordering, and every group carries its own score
        assert [g["replica_id"] for g in st["groups"]][0] == "g3"
        by_id = {g["replica_id"]: g for g in st["groups"]}
        assert all(abs(by_id[f"g{i}"]["straggler_score"]) < 3.0
                   for i in range(3))
        # per-stage fleet medians come from the baseline
        assert st["fleet"]["stage_median_ms"]["fetch"] == 25.0

    def test_single_group_fleet_no_nan(self):
        agg = FleetAggregator()
        agg.ingest(mk_digest("only", 250.0, ring=100.0), now_ms=0)
        st = agg.aggregate(now_ms=1)
        g = st["groups"][0]
        assert g["straggler_score"] == 0.0
        assert np.isfinite(g["straggler_score"])
        assert st["fleet"]["p50_ms"] == 250.0
        assert json.loads(json.dumps(st))  # JSON-safe end to end

    def test_healer_excluded_from_baseline_and_ranking(self):
        agg = FleetAggregator()
        for i in range(3):
            agg.ingest(mk_digest(f"g{i}", 100.0, ring=10.0), now_ms=0)
        # The healer is 50x slower — legitimately: it is healing.
        agg.ingest(mk_digest("healer", 5000.0, ring=10.0,
                             healing=True), now_ms=0)
        st = agg.aggregate(now_ms=1)
        assert st["fleet"]["groups"] == 4
        assert st["fleet"]["baseline_groups"] == 3
        by_id = {g["replica_id"]: g for g in st["groups"]}
        assert by_id["healer"]["baseline"] is False
        assert by_id["healer"]["straggler_score"] == 0.0
        assert by_id["healer"]["straggler_stage"] == "heal"
        # ...and it can never be named THE straggler
        assert st["straggler"]["replica_id"] != "healer"
        # the baseline quantiles ignore it
        assert st["fleet"]["max_ms"] == 100.0

    def test_degraded_group_excluded_with_reason(self):
        agg = FleetAggregator()
        agg.ingest(mk_digest("ok", 100.0), now_ms=0)
        agg.ingest(mk_digest("deg", 900.0, capacity=0.75), now_ms=0)
        st = agg.aggregate(now_ms=1)
        by_id = {g["replica_id"]: g for g in st["groups"]}
        assert by_id["deg"]["straggler_stage"] == "degraded"
        assert by_id["deg"]["baseline"] is False
        assert st["fleet"]["baseline_groups"] == 1

    def test_stale_group_drops_out_of_aggregates(self):
        agg = FleetAggregator(stale_ms=1000)
        agg.ingest(mk_digest("fresh", 100.0), now_ms=5000)
        agg.ingest(mk_digest("silent", 100.0), now_ms=0)
        st = agg.aggregate(now_ms=5100)
        assert [g["replica_id"] for g in st["groups"]] == ["fresh"]
        # prune() also reclaims the ring memory
        agg.prune(now_ms=5100)
        assert agg.group_ids() == ["fresh"]

    def test_remove_is_immediate(self):
        agg = FleetAggregator()
        agg.ingest(mk_digest("a", 100.0), now_ms=0)
        agg.ingest(mk_digest("b", 100.0), now_ms=0)
        agg.note_commit_counts("b", 5, 0)
        agg.remove("b")
        st = agg.aggregate(now_ms=1)
        assert [g["replica_id"] for g in st["groups"]] == ["a"]
        assert "b" not in agg.commit_counts()

    def test_ring_bounded_latest_wins(self):
        agg = FleetAggregator(ring=4)
        for step in range(10):
            agg.ingest(mk_digest("a", 100.0 + step, step=step),
                       now_ms=step)
        st = agg.aggregate(now_ms=10)
        assert st["groups"][0]["step"] == 9
        assert st["groups"][0]["step_wall_ms"] == 109.0

    def test_uniform_fleet_straggler_matches_table_order(self):
        """Tied scores (uniform fleet -> all 0.0) must name the SAME
        group as the table's first row — smallest id, the native
        aggregator's tie-break. A max()-style pick of the LARGEST id
        here once diverged from both."""
        agg = FleetAggregator()
        for rid in ("c", "a", "b"):
            agg.ingest(mk_digest(rid, 100.0), now_ms=0)
        st = agg.aggregate(now_ms=1)
        assert st["straggler"]["replica_id"] == "a"
        assert st["straggler"]["replica_id"] == \
            st["groups"][0]["replica_id"]

    def test_staleness_slo_widens_retention(self):
        """A staleness threshold at/past the retention window could
        never breach (the group is dropped from the aggregate before
        the check sees it) — constructing the aggregator WITH the SLO
        config widens retention to 2x the threshold, mirroring the
        native lighthouse constructor."""
        cfg = SLOConfig(staleness_ms=120_000.0)
        agg = FleetAggregator(stale_ms=60_000, slo=cfg)
        agg.ingest(mk_digest("quiet", 100.0), now_ms=0)
        # 150s silent: past the default 60s retention, but visible
        # under the widened window — and breaching.
        st = agg.aggregate(now_ms=150_000)
        assert [g["replica_id"] for g in st["groups"]] == ["quiet"]
        eng = SLOEngine(cfg)
        assert [b["slo"] for b in eng.evaluate(st)] == ["staleness"]
        # ...and past 2x the threshold the group finally ages out.
        assert agg.aggregate(now_ms=260_000)["groups"] == []

    def test_empty_fleet_aggregate_is_sane(self):
        st = FleetAggregator().aggregate(now_ms=1)
        assert st["fleet"]["groups"] == 0
        assert st["fleet"]["p95_ms"] == 0.0
        assert st["straggler"]["replica_id"] == ""
        assert st["groups"] == []


# ---------------------------------------------------------------- SLOs


class TestSLOConfig:
    def test_spec_roundtrip_and_separators(self):
        cfg = SLOConfig.from_spec(
            "step_p95_ms=2500, commit_rate=0.95; heal_ms=60000")
        assert cfg.step_p95_ms == 2500.0
        assert cfg.commit_rate == 0.95
        assert cfg.heal_ms == 60000.0
        assert cfg.publish_lag_ms is None
        assert cfg.enabled()
        assert SLOConfig.from_spec(cfg.spec()).spec() == cfg.spec()

    def test_unknown_key_raises(self):
        with pytest.raises(ValueError, match="bad SLO spec"):
            SLOConfig.from_spec("step_p95ms=100")  # typo'd key
        with pytest.raises(ValueError):
            SLOConfig.from_spec("nonsense")

    def test_non_decimal_threshold_raises(self):
        """float() accepts spellings ("2_500", "nan") the C++ atof
        parses DIFFERENTLY — the strict gate rejects anything the two
        sides could disagree on."""
        for bad in ("step_p95_ms=2_500", "heal_ms=nan",
                    "commit_rate=", "staleness_ms=10s",
                    # negative = "disabled" to the C++ parser but a
                    # live always-breaching bound to the Python
                    # engine — rejected so they can't disagree
                    "step_p95_ms=-1"):
            with pytest.raises(ValueError):
                SLOConfig.from_spec(bad)
        # plain decimals, signs, and exponents still parse
        assert SLOConfig.from_spec(
            "step_p95_ms=2.5e3").step_p95_ms == 2500.0

    def test_empty_spec_disabled(self):
        cfg = SLOConfig.from_spec("")
        assert not cfg.enabled()
        assert cfg.spec() == ""

    def test_from_env(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_SLO", "staleness_ms=30000")
        assert SLOConfig.from_env().staleness_ms == 30000.0


class TestSLOEngine:
    def _status(self, **over):
        agg = FleetAggregator()
        agg.ingest(mk_digest("fast", 100.0, ring=10.0), now_ms=0)
        agg.ingest(mk_digest("slow", 4000.0, ring=3500.0, step=7,
                             **over.pop("slow_kw", {})), now_ms=0)
        return agg.aggregate(now_ms=1)

    def test_step_p95_breach_lands_on_the_straggler(self):
        eng = SLOEngine(SLOConfig(step_p95_ms=1000.0))
        fresh = eng.evaluate(self._status())
        assert len(fresh) == 1
        b = fresh[0]
        assert b["slo"] == "step_p95"
        assert b["replica_id"] == "slow"
        assert b["step"] == 7
        assert b["value"] == 4000.0
        assert eng.breaches_for("slow") == ["step_p95"]
        assert eng.breaches_for("fast") == []

    def test_dedup_per_slo_group_step(self):
        eng = SLOEngine(SLOConfig(step_p95_ms=1000.0))
        st = self._status()
        assert len(eng.evaluate(st)) == 1
        # same (slo, group, step) persisting -> no NEW breach...
        assert eng.evaluate(st) == []
        assert eng.breaches_total == 1
        # ...but it is still ACTIVE (the slo_breach gauge stays up)
        assert eng.breaches_for("slow") == ["step_p95"]
        # a new step re-arms the event
        agg = FleetAggregator()
        agg.ingest(mk_digest("fast", 100.0), now_ms=0)
        agg.ingest(mk_digest("slow", 4000.0, step=8), now_ms=0)
        assert len(eng.evaluate(agg.aggregate(now_ms=1))) == 1
        assert eng.breaches_total == 2

    def test_heal_publish_staleness_thresholds(self):
        agg = FleetAggregator(stale_ms=120_000)
        agg.ingest(mk_digest("a", 100.0, heal_last_ms=90_000.0),
                   now_ms=60_000)
        agg.ingest(mk_digest("b", 100.0, publish_last_ms=9_000.0),
                   now_ms=60_000)
        agg.ingest(mk_digest("c", 100.0), now_ms=0)  # silent 60s
        st = agg.aggregate(now_ms=60_000)
        eng = SLOEngine(SLOConfig(heal_ms=60_000.0,
                                  publish_lag_ms=5_000.0,
                                  staleness_ms=30_000.0))
        fresh = eng.evaluate(st)
        got = {(b["slo"], b["replica_id"]) for b in fresh}
        assert got == {("heal", "a"), ("publish_lag", "b"),
                       ("staleness", "c")}

    def test_commit_rate_needs_min_samples(self):
        agg = FleetAggregator()
        agg.ingest(mk_digest("a", 100.0), now_ms=0)
        st = agg.aggregate(now_ms=1)
        eng = SLOEngine(SLOConfig(commit_rate=0.9,
                                  min_commit_samples=8))
        # 3 commits, 4 aborts: terrible rate but under the sample floor
        assert eng.evaluate(st, {"a": (3, 4)}) == []
        fresh = eng.evaluate(st, {"a": (5, 5)})
        assert [b["slo"] for b in fresh] == ["commit_rate"]
        assert fresh[0]["value"] == 0.5

    def test_no_slos_no_breaches(self):
        eng = SLOEngine(SLOConfig())
        assert eng.evaluate(self._status()) == []
        assert eng.active == []


# ----------------------------------------------------------- renderers


# The /fleet/metrics exposition names, frozen: lighthouse.cc's
# fleet_metrics_text emits the SAME set — a drift between the two
# spellings breaks scrape configs silently.
FLEET_METRIC_NAMES = frozenset([
    "torchft_fleet_groups", "torchft_fleet_step_ms",
    "torchft_fleet_step_ms_max", "torchft_fleet_slo_breach",
    "torchft_fleet_slo_breaches_total",
    "torchft_fleet_sdc_quarantined",
    "torchft_fleet_sdc_verdicts_total",
    "torchft_fleet_rebalance_groups",
    "torchft_fleet_rebalance_seq",
    "torchft_fleet_rebalance_fraction",
    "torchft_fleet_stage_median_ms",
    "torchft_fleet_straggler_score", "torchft_fleet_group_step_ms",
    # publication relay tier (docs/design/serving.md)
    "torchft_fleet_relays", "torchft_fleet_relay_children",
    "torchft_fleet_relay_lag_gens_max",
    "torchft_fleet_relay_child_count", "torchft_fleet_relay_lag_gens",
])


def _exposition_names(text):
    names = set()
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            names.add(line.split()[2])
    return names


class TestRenderers:
    def _status(self):
        agg = FleetAggregator()
        agg.ingest(mk_digest("g0", 100.0, ring=10.0,
                             trace_addr="http://a:1"), now_ms=0)
        agg.ingest(mk_digest("g1", 900.0, ring=800.0,
                             trace_addr="http://b:2"), now_ms=0)
        agg.ingest(mk_digest("h", 5000.0, healing=True,
                             trace_addr="http://a:1"), now_ms=0)
        return agg.aggregate(now_ms=1)

    def test_prometheus_names_frozen(self):
        text = status_prometheus(self._status(), slo_active=1,
                                 slo_breaches_total=3)
        assert _exposition_names(text) == FLEET_METRIC_NAMES
        assert 'torchft_fleet_straggler_score{replica_id="g1"}' in text
        assert 'torchft_fleet_step_ms{quantile="0.95"}' in text
        assert "torchft_fleet_slo_breach 1.0" in text
        assert "torchft_fleet_slo_breaches_total 3.0" in text
        # every family carries HELP + TYPE
        helps = {l.split()[2] for l in text.splitlines()
                 if l.startswith("# HELP ")}
        assert helps == FLEET_METRIC_NAMES

    def test_prometheus_label_escaping(self):
        agg = FleetAggregator()
        agg.ingest(mk_digest('g"q\\z', 100.0), now_ms=0)
        # a raw newline in a replica_id must not split the sample line
        agg.ingest(mk_digest("g\nnl", 100.0), now_ms=0)
        text = status_prometheus(agg.aggregate(now_ms=1))
        assert 'replica_id="g\\"q\\\\z"' in text
        assert 'replica_id="g\\nnl"' in text
        assert "\ng\nnl" not in text

    def test_relay_tier_rides_aggregate_and_exposition(self):
        agg = FleetAggregator()
        agg.ingest(mk_digest("g0", 100.0), now_ms=0)
        agg.note_relays([
            {"id": "r1", "addr": "http://r1/publish", "children": 3,
             "lag_gens": 0, "age_s": 0.1},
            {"id": "r2", "addr": "http://r2/publish", "children": 1,
             "lag_gens": 2, "age_s": 0.4},
        ])
        st = agg.aggregate(now_ms=1)
        assert st["fleet"]["relays"] == 2
        assert st["fleet"]["relay_children"] == 4
        assert st["fleet"]["relay_lag_gens_max"] == 2
        assert [r["id"] for r in st["relays"]] == ["r1", "r2"]
        text = status_prometheus(st)
        assert _exposition_names(text) == FLEET_METRIC_NAMES
        assert "torchft_fleet_relays 2.0" in text
        assert "torchft_fleet_relay_children 4.0" in text
        assert "torchft_fleet_relay_lag_gens_max 2.0" in text
        assert 'torchft_fleet_relay_child_count{relay_id="r1"} 3.0' \
            in text
        assert 'torchft_fleet_relay_lag_gens{relay_id="r2"} 2.0' \
            in text

    def test_fleet_table_renders_ranked_rows(self):
        st = self._status()
        table = format_fleet_table(
            st, breaches=[{"slo": "step_p95", "replica_id": "g1",
                           "value": 900.0, "threshold": 500.0,
                           "step": 5}])
        lines = table.splitlines()
        assert "straggler: g1" in table
        assert "SLO BREACH: step_p95 on g1" in table
        # worst-first rows; the healer is flagged
        g1_row = next(i for i, l in enumerate(lines)
                      if l.startswith("g1"))
        g0_row = next(i for i, l in enumerate(lines)
                      if l.startswith("g0"))
        assert g1_row < g0_row
        assert any(l.endswith("HEAL") for l in lines)

    def test_resolve_trace_addrs_dedups(self):
        addrs = resolve_trace_addrs(self._status())
        assert addrs == ["http://b:2", "http://a:1"] or \
            set(addrs) == {"http://a:1", "http://b:2"}
        assert len(addrs) == 2
        assert resolve_trace_addrs({"groups": []}) == []


# ----------------------------------------------- tracer stage totals


class TestStageTotals:
    def test_sums_per_stage_for_newest_step(self):
        tr = tracing.Tracer(steps=4, enabled=True)
        tr.set_context(step=3)
        with tr.span("ring"):
            time.sleep(0.002)
        with tr.span("ring"):
            pass
        with tr.span("vote"):
            pass
        tr.set_context(step=4)
        with tr.span("put"):
            pass
        newest = tr.stage_totals()
        assert set(newest) == {"put"}
        old = tr.stage_totals(step=3)
        assert set(old) == {"ring", "vote"}
        assert old["ring"] >= 2.0  # two spans, one slept 2ms

    def test_empty_or_disabled_ring(self):
        assert tracing.Tracer(steps=4, enabled=True).stage_totals() == {}
        tr = tracing.Tracer(steps=4, enabled=False)
        with tr.span("ring"):
            pass
        assert tr.stage_totals() == {}


# ------------------------------------------------- manager-side halves


class _DigestServer:
    """Captures the manager's set_status/set_digest pushes."""

    def __init__(self):
        self.digests = []

    def set_status(self, *a, **k):
        pass

    def set_digest(self, **kw):
        self.digests.append(kw)

    def lighthouse_redials(self):  # metrics() reads this
        return 0


class TestDigestPush:
    def test_first_boundary_skipped_then_wall_reported(self):
        m = make_manager()
        try:
            srv = _DigestServer()
            m._manager_server = srv
            m._publish_status()
            assert srv.digests == []  # no previous boundary: no wall
            time.sleep(0.01)
            m._publish_status()
            assert len(srv.digests) == 1
            d = srv.digests[0]
            assert d["step_wall_ms"] >= 10.0
            assert d["trace_addr"] == m._ckpt_server.address()
            assert d["capacity_fraction"] == 1.0
            assert d["healing"] is False
            assert d["heal_last_ms"] == 0.0
        finally:
            m._manager_server = None
            m.shutdown()

    def test_heal_delta_gated_on_count(self):
        m = make_manager()
        try:
            srv = _DigestServer()
            m._manager_server = srv
            m._publish_status()
            # A heal completed this boundary: count bumped, ms accrued.
            with m._metrics_lock:
                m._metrics["heal_count"] += 1
                m._metrics["heal_ms_total"] += 2500.0
            m._publish_status()
            assert srv.digests[-1]["heal_last_ms"] == 2500.0
            # ms drift WITHOUT a completed heal must not mint one.
            with m._metrics_lock:
                m._metrics["heal_ms_total"] += 400.0
            m._publish_status()
            assert srv.digests[-1]["heal_last_ms"] == 0.0
        finally:
            m._manager_server = None
            m.shutdown()

    def test_stage_splits_come_from_tracer(self):
        m = make_manager(tracing=True)
        try:
            srv = _DigestServer()
            m._manager_server = srv
            m._publish_status()
            m._tracer.set_context(step=m._step)
            with m._tracer.span("ring"):
                time.sleep(0.002)
            with m._tracer.span("fetch_wait"):
                time.sleep(0.001)
            m._publish_status()
            d = srv.digests[-1]
            assert d["ring_ms"] >= 2.0
            assert d["fetch_ms"] >= 1.0  # dispatch + wait folded
            assert d["put_ms"] == 0.0
        finally:
            m._manager_server = None
            m.shutdown()

    def test_fleet_telemetry_off_pushes_nothing(self):
        m = make_manager(fleet_telemetry=False)
        try:
            srv = _DigestServer()
            m._manager_server = srv
            m._publish_status()
            m._publish_status()
            assert srv.digests == []
        finally:
            m._manager_server = None
            m.shutdown()

    def test_env_default_knob(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_FLEET_TELEMETRY", "0")
        m = make_manager()
        try:
            assert m._fleet_telemetry is False
        finally:
            m.shutdown()
        monkeypatch.delenv("TORCHFT_FLEET_TELEMETRY")
        m = make_manager()
        try:
            assert m._fleet_telemetry is True  # default ON
        finally:
            m.shutdown()

    def test_duck_typed_server_without_set_digest_is_fine(self):
        m = make_manager()
        try:
            m._manager_server = object()  # no set_digest, no set_status
            m._publish_status()  # must not raise
        finally:
            m._manager_server = None
            m.shutdown()


class TestFleetHintConsumption:
    def test_gauges_refresh_every_round(self):
        m = make_manager()
        try:
            m._consume_fleet_hint(hint(fleet_p95_ms=850.0,
                                       straggler_score=-0.4,
                                       fleet_groups=16,
                                       straggler_stage="fetch",
                                       straggler_id="g9"))
            mx = m.metrics()
            assert mx["fleet_p95_ms"] == 850.0
            assert mx["straggler_score"] == -0.4
            assert mx["fleet_groups"] == 16.0
            assert mx["slo_breach"] == 0.0
            assert mx["slo_breaches_total"] == 0.0
            assert m.metrics_info()["straggler_stage"] == "fetch"
            # a later hint-less round zeroes the gauges back
            m._consume_fleet_hint(hint())
            assert m.metrics()["fleet_p95_ms"] == 0.0
            assert m.metrics_info()["straggler_stage"] == ""
        finally:
            m.shutdown()

    def test_slo_breach_dumps_flight_once_per_step(self, tmp_path,
                                                   monkeypatch):
        monkeypatch.setenv("TORCHFT_FLIGHT_DIR", str(tmp_path))
        m = make_manager()
        try:
            h = hint(fleet_p95_ms=4000.0, straggler_score=11.0,
                     straggler_stage="ring", slo_breach="step_p95")
            m._consume_fleet_hint(h)
            mx = m.metrics()
            assert mx["slo_breach"] == 1.0
            assert mx["slo_breaches_total"] == 1.0
            dumps = list(tmp_path.glob("*.json"))
            assert len(dumps) == 1
            side = json.loads(dumps[0].read_text())["torchft"]
            assert side["reason"] == "slo_breach_step_p95"
            assert side["extra"]["stage"] == "ring"
            assert side["extra"]["fleet_p95_ms"] == 4000.0
            # the breach persists across rounds of the same step: the
            # counter, event log, and dump must NOT repeat
            m._consume_fleet_hint(h)
            assert m.metrics()["slo_breaches_total"] == 1.0
            assert len(list(tmp_path.glob("*.json"))) == 1
            events = [e for e in m.history()
                      if e.get("event") == "slo_breach"]
            assert len(events) == 1
            # ...but a new step re-arms it (the real flow bumps both
            # in step(): the counter and the tracer's context)
            m._step += 1
            m._tracer.set_context(step=m._step)
            m._consume_fleet_hint(h)
            assert m.metrics()["slo_breaches_total"] == 2.0
            assert len(list(tmp_path.glob("*.json"))) == 2
        finally:
            m.shutdown()

    def test_multi_breach_hint_counts_each_slo(self):
        m = make_manager()
        try:
            m._consume_fleet_hint(
                hint(slo_breach="step_p95,staleness"))
            assert m.metrics()["slo_breaches_total"] == 2.0
        finally:
            m.shutdown()

    def test_duck_typed_quorum_is_hintless(self):
        m = make_manager()
        try:
            m._consume_fleet_hint(MagicMock())  # attrs are all Mocks
            mx = m.metrics()
            assert mx["fleet_p95_ms"] == 0.0
            assert mx["slo_breach"] == 0.0
            assert m.metrics_info()["straggler_stage"] == ""
        finally:
            m.shutdown()


# ------------------------------------------ tracefleet --fleet resolver


class _FleetStub:
    """A stub lighthouse serving ONLY /fleet/status.json."""

    def __init__(self, status):
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path != "/fleet/status.json":
                    self.send_error(404)
                    return
                body = json.dumps(stub.status).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.status = status
        self.srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(target=self.srv.serve_forever,
                                       daemon=True)
        self.thread.start()

    @property
    def address(self):
        return f"127.0.0.1:{self.srv.server_address[1]}"

    def close(self):
        self.srv.shutdown()
        self.srv.server_close()


class TestTracefleetFleetResolution:
    def _import_tracefleet(self):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import tracefleet
        finally:
            sys.path.pop(0)
        return tracefleet

    def test_resolves_and_merges_from_fleet_status(self, tmp_path):
        tracefleet = self._import_tracefleet()
        m = make_manager(replica_id="fla0")
        stub = None
        try:
            m.step()
            m.should_commit()
            agg = FleetAggregator()
            agg.ingest(mk_digest(
                "fla0", 100.0,
                trace_addr=m._ckpt_server.address()), now_ms=0)
            stub = _FleetStub(agg.aggregate(now_ms=1))
            out = tmp_path / "fleet.json"
            rc = tracefleet.main(["--fleet", stub.address,
                                  "--out", str(out)])
            assert rc == 0
            merged = json.loads(out.read_text())
            names = {ev["args"]["name"] for ev in merged["traceEvents"]
                     if ev.get("ph") == "M"
                     and ev.get("name") == "process_name"}
            assert names == {"fla0"}
        finally:
            if stub is not None:
                stub.close()
            m.shutdown()

    def test_fleet_resolution_failure_is_not_fatal_with_args(
            self, tmp_path):
        tracefleet = self._import_tracefleet()
        m = make_manager(replica_id="fla1")
        try:
            m.step()
            m.should_commit()
            out = tmp_path / "fleet.json"
            # unreachable --fleet + a good explicit address: merge wins
            rc = tracefleet.main(["--fleet", "127.0.0.1:1",
                                  m._ckpt_server.address(),
                                  "--out", str(out), "--timeout", "2"])
            assert rc == 0
            assert json.loads(out.read_text())["traceEvents"]
        finally:
            m.shutdown()

    def test_resolve_helper_reads_trace_addrs(self):
        tracefleet = self._import_tracefleet()
        agg = FleetAggregator()
        agg.ingest(mk_digest("a", 100.0, trace_addr="http://x:1"),
                   now_ms=0)
        stub = _FleetStub(agg.aggregate(now_ms=1))
        try:
            got = tracefleet.resolve_from_fleet(stub.address)
            assert got == ["http://x:1"]
        finally:
            stub.close()


# ------------------------------------------------------ benchdiff units


class TestBenchdiff:
    def _bd(self):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import benchdiff
        finally:
            sys.path.pop(0)
        return benchdiff

    def test_direction_vocabulary(self):
        bd = self._bd()
        assert bd.direction_of("steps_per_s") == 1
        assert bd.direction_of("speedup_vs_exact") == 1
        assert bd.direction_of("achieved_tflops") == 1
        assert bd.direction_of("allreduce_ms_avg") == -1
        assert bd.direction_of("stages_ms.ring") == -1
        assert bd.direction_of("recovery_wall_clock_s") == -1
        assert bd.direction_of("n_groups") is None
        assert bd.direction_of("seq_len") is None
        assert bd.direction_of("value", unit="steps/s") == 1
        assert bd.direction_of("value", unit="GB") == -1

    def test_driver_wrapper_and_jsonl_both_parse(self, tmp_path):
        bd = self._bd()
        row = {"metric": "m", "value": 1.0, "unit": "steps/s"}
        wrapped = tmp_path / "BENCH_r01.json"
        wrapped.write_text(json.dumps(
            {"n": 1, "cmd": "x", "rc": 0,
             "tail": "noise\n" + json.dumps(row) + "\n"}))
        raw = tmp_path / "rows.jsonl"
        raw.write_text(json.dumps(row) + "\n")
        assert bd.parse_bench_file(str(wrapped)) == {"m": row}
        assert bd.parse_bench_file(str(raw)) == {"m": row}

    def test_regression_direction_aware(self, tmp_path):
        bd = self._bd()
        old = {"m": {"metric": "m", "steps_per_s": 1.0,
                     "ring_ms": 100.0}}
        # throughput down 50% AND latency up 50%: two regressions
        new = {"m": {"metric": "m", "steps_per_s": 0.5,
                     "ring_ms": 150.0}}
        d = bd.diff_rows(old, new, threshold=0.10)
        assert {e["key"] for e in d["regressions"]} == \
            {"steps_per_s", "ring_ms"}
        # both moving the GOOD way: improvements, never fatal
        better = {"m": {"metric": "m", "steps_per_s": 2.0,
                        "ring_ms": 50.0}}
        d = bd.diff_rows(old, better, threshold=0.10)
        assert not d["regressions"]
        assert len(d["improvements"]) == 2

    def test_provenance_mismatch_skips_not_gates(self):
        """A rig/schema change or an error stub must read as skipped,
        never as a regression: a TPU round followed by a CPU-only rig
        would otherwise permanently fail the trajectory gate."""
        bd = self._bd()
        tpu = {"m": {"metric": "m", "steps_per_s": 100.0,
                     "schema": "tft-bench-2", "platform": "tpu"}}
        cpu = {"m": {"metric": "m", "steps_per_s": 1.0,
                     "schema": "tft-bench-2", "platform": "cpu"}}
        d = bd.diff_rows(tpu, cpu, threshold=0.10)
        assert not d["regressions"]
        assert d["skipped"] and "rig changed" in d["skipped"][0]["reason"]
        # rows predating the provenance stamp are schema v1
        v1 = {"m": {"metric": "m", "steps_per_s": 100.0}}
        d = bd.diff_rows(v1, cpu, threshold=0.10)
        assert not d["regressions"]
        assert "schema changed" in d["skipped"][0]["reason"]
        # an error stub is a placeholder, not a measurement
        err = {"m": {"metric": "m", "steps_per_s": -1.0,
                     "schema": "tft-bench-2", "platform": "cpu",
                     "error": "native control plane unavailable"}}
        d = bd.diff_rows(cpu, err, threshold=0.10)
        assert not d["regressions"]
        assert d["skipped"][0]["reason"] == "error row"
        # same rig, same schema, no error: still gates normally
        slow = {"m": {"metric": "m", "steps_per_s": 10.0,
                      "schema": "tft-bench-2", "platform": "cpu"}}
        d = bd.diff_rows(cpu, slow, threshold=0.10)
        assert not d["skipped"]
        assert len(d["improvements"]) == 1

    def test_host_shape_change_skips_not_gates(self):
        """Same "cpu" platform string on a different machine shape is
        still a rig change: a 1-core container cannot reproduce a
        16-core round's throughput rows. Strict like schema — an
        unstamped row's host is unknown, so stamped-vs-unstamped also
        skips rather than manufacturing a permanent regression."""
        bd = self._bd()

        def row(v, cpus=None):
            r = {"metric": "m", "steps_per_s": v,
                 "schema": "tft-bench-2", "platform": "cpu"}
            if cpus is not None:
                r["host_cpus"] = cpus
            return {"m": r}

        # both stamped, shapes differ -> skipped
        d = bd.diff_rows(row(100.0, cpus=16), row(10.0, cpus=1), 0.10)
        assert not d["regressions"]
        assert "host shape changed: 16 -> 1 cpus" == \
            d["skipped"][0]["reason"]
        # unstamped old vs stamped new (rows predate the stamp) ->
        # skipped, never a regression
        d = bd.diff_rows(row(100.0), row(10.0, cpus=1), 0.10)
        assert not d["regressions"]
        assert "unstamped -> 1 cpus" in d["skipped"][0]["reason"]
        # both stamped, same shape -> gates normally
        d = bd.diff_rows(row(100.0, cpus=1), row(10.0, cpus=1), 0.10)
        assert not d["skipped"]
        assert len(d["regressions"]) == 1

    def test_trajectory_gates_newest_pair_only(self, tmp_path):
        bd = self._bd()

        def write(n, v):
            (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(
                {"tail": json.dumps(
                    {"metric": "m", "value": v,
                     "unit": "steps/s"})}))

        # old regression (r1->r2), then recovery (r2->r3): gate passes
        write(1, 1.0)
        write(2, 0.4)
        write(3, 1.1)
        assert bd.main([str(tmp_path)]) == 0
        assert bd.main([str(tmp_path), "--all"]) == 1
        # newest pair regressing fails either way
        write(4, 0.2)
        assert bd.main([str(tmp_path)]) == 1

    def test_file_plus_directory_is_a_cli_error(self, tmp_path):
        """A file+directory pair must die as an argparse error, not an
        IsADirectoryError traceback from open('.')."""
        bd = self._bd()
        f = tmp_path / "a.json"
        f.write_text(json.dumps({"metric": "m", "value": 1.0}))
        with pytest.raises(SystemExit) as exc:
            bd.main([str(f), str(tmp_path)])
        assert exc.value.code == 2  # argparse usage error

    def test_added_removed_metrics_not_fatal(self, tmp_path):
        bd = self._bd()
        a = tmp_path / "a.json"
        b = tmp_path / "b.json"
        a.write_text(json.dumps({"metric": "gone", "value": 1.0}))
        b.write_text(json.dumps({"metric": "born", "value": 1.0}))
        assert bd.main([str(a), str(b)]) == 0


# ------------------------------------------------------- native rounds


@requires_native
@pytest.mark.integration
@pytest.mark.nightly
@pytest.mark.slow
class TestNativeFleetDrive:
    """The ISSUE-15 acceptance drive at the control-plane level: 4
    groups piggyback digests on real quorum RPC beats, one is
    artificially slowed (a fat ring stage), and the lighthouse must
    rank it, attribute it, echo the step-p95 breach to IT alone, and
    serve the same numbers over /fleet/status.json + /fleet/metrics
    that the pure-Python mirror computes from the same digests."""

    def _drive_round(self, servers, step, walls, rings):
        from torchft_tpu._native import ManagerClient

        results = {}

        def run(gid, srv):
            srv.set_digest(step=step, step_wall_ms=walls[gid],
                           fetch_ms=25.0, ring_ms=rings[gid],
                           put_ms=5.0, vote_ms=2.0,
                           trace_addr=f"http://{gid}:1")
            client = ManagerClient(srv.address())
            results[gid] = client.quorum(
                rank=0, step=step,
                checkpoint_server_addr=f"ckpt_{gid}",
                timeout_ms=20_000)

        ts = [threading.Thread(target=run, args=(gid, srv))
              for gid, srv in servers.items()]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return results

    def test_four_group_straggler_attribution_and_slo_echo(self):
        from torchft_tpu._native import Lighthouse, ManagerServer

        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=4,
                        join_timeout_ms=2000, quorum_tick_ms=10,
                        slo="step_p95_ms=1000")
        servers = {}
        try:
            for i in range(4):
                gid = f"g{i}"
                servers[gid] = ManagerServer(
                    gid, lh.address(), store_addr=f"store_{gid}",
                    bind="127.0.0.1:0", world_size=1)
            walls = {"g0": 100.0, "g1": 110.0, "g2": 105.0,
                     "g3": 3000.0}
            rings = {"g0": 10.0, "g1": 12.0, "g2": 11.0, "g3": 2500.0}
            self._drive_round(servers, 1, walls, rings)
            time.sleep(0.3)  # let the 200ms aggregate cache expire
            res = self._drive_round(servers, 2, walls, rings)

            # every group sees the same fleet quantiles in its hint
            for gid, r in res.items():
                assert r.fleet_groups == 4, gid
                assert r.fleet_p95_ms == 3000.0, gid
                assert r.straggler_id == "g3", gid
            # the slowed group leads the ranking, attributed to ring,
            # and the step-p95 breach is echoed to IT alone
            assert res["g3"].straggler_score > 10.0
            assert res["g3"].straggler_stage == "ring"
            assert "step_p95" in res["g3"].slo_breach
            for gid in ("g0", "g1", "g2"):
                assert res[gid].slo_breach == "", gid
                assert abs(res[gid].straggler_score) < 3.0, gid

            # /fleet/status.json agrees, and matches the Python mirror
            # fed the same digests (the two implementations must rank
            # identically)
            with urllib.request.urlopen(
                    f"http://{lh.address()}/fleet/status.json",
                    timeout=10) as resp:
                native = json.loads(resp.read())
            assert native["straggler"]["replica_id"] == "g3"
            assert native["straggler"]["stage"] == "ring"
            assert [g["replica_id"] for g in native["groups"]][0] \
                == "g3"
            mirror = FleetAggregator()
            for gid in servers:
                mirror.ingest(mk_digest(gid, walls[gid], fetch=25.0,
                                        ring=rings[gid], put=5.0,
                                        vote=2.0, step=2), now_ms=0)
            st = mirror.aggregate(now_ms=1)
            for ng, pg in zip(native["groups"], st["groups"]):
                assert ng["replica_id"] == pg["replica_id"]
                assert ng["straggler_score"] == pytest.approx(
                    pg["straggler_score"], abs=1e-3)
                assert ng["straggler_stage"] == pg["straggler_stage"]
            assert native["fleet"]["p95_ms"] == st["fleet"]["p95_ms"]
            assert native["slo"]["breaches_total"] >= 1

            # /fleet/metrics serves the frozen exposition names
            with urllib.request.urlopen(
                    f"http://{lh.address()}/fleet/metrics",
                    timeout=10) as resp:
                text = resp.read().decode()
            assert _exposition_names(text) == FLEET_METRIC_NAMES
        finally:
            for srv in servers.values():
                srv.shutdown()
            lh.shutdown()

    def test_churn_soak_keeps_fleet_status_coherent(self):
        """Graceful churn (the ChurnOrchestrator's notice leg) must
        withdraw departed groups from /fleet/status.json immediately —
        no phantom straggler — while survivors keep aggregating."""
        from torchft_tpu._native import Lighthouse, ManagerServer
        from torchft_tpu.chaos import ChurnOrchestrator

        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1,
                        join_timeout_ms=300, quorum_tick_ms=10)
        gids = [f"c{i}" for i in range(4)]
        servers = {}

        def status_ids():
            with urllib.request.urlopen(
                    f"http://{lh.address()}/fleet/status.json",
                    timeout=10) as resp:
                st = json.loads(resp.read())
            return {g["replica_id"] for g in st["groups"]}

        def spawn(gid):
            servers[gid] = ManagerServer(
                gid, lh.address(), store_addr=f"store_{gid}",
                bind="127.0.0.1:0", world_size=1)
            servers[gid].set_digest(step=1, step_wall_ms=100.0,
                                    ring_ms=10.0,
                                    trace_addr=f"http://{gid}:1")

        def drain(gid):
            srv = servers.pop(gid, None)
            if srv is not None:
                srv.farewell()
                srv.shutdown()

        try:
            for gid in gids:
                spawn(gid)
            time.sleep(0.8)  # beats deliver the digests
            assert status_ids() == set(gids)

            orch = ChurnOrchestrator(
                seed=77, groups=gids, rate_per_min=600.0,
                graceful_frac=1.0, notify=drain, replace=spawn,
                replace_delay_s=0.3, min_live=2)
            t0 = time.monotonic()
            while time.monotonic() - t0 < 6.0:
                orch.tick(time.monotonic() - t0)
                time.sleep(0.05)
                # Coherence invariant: a farewelled group is withdrawn
                # IMMEDIATELY; live groups may lag one beat, so only
                # the no-phantom direction is exact.
                assert status_ids() <= set(servers), (
                    "departed group lingering in /fleet/status.json")
            assert orch.notices >= 2, "soak drove no churn"
            time.sleep(0.8)
            assert status_ids() == set(servers)
        finally:
            for srv in servers.values():
                srv.shutdown()
            lh.shutdown()
