"""Durable disk checkpoint tests: atomic write, crash-mid-save leaves the
previous checkpoint intact, latest() selection (verdict r1 #9 — this module
shipped untested in round 1)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from torchft_tpu import checkpoint_io


def user_state(val=1.0):
    return {
        "params": {"w": jnp.full((4, 4), val), "b": jnp.zeros((4,))},
        "opt": [jnp.ones((2,)), np.int64(3)],
    }


class TestSaveLoad:
    def test_round_trip_with_manager_state(self, tmp_path):
        path = str(tmp_path / "ckpt_7")
        checkpoint_io.save(path, user_state(2.5),
                           {"step": 7, "batches_committed": 21})
        user, mgr = checkpoint_io.load(path, target=user_state(),
                                       device_put=False)
        np.testing.assert_array_equal(user["params"]["w"],
                                      np.full((4, 4), 2.5))
        assert mgr == {"step": 7, "batches_committed": 21}

    def test_default_manager_state(self, tmp_path):
        path = str(tmp_path / "ckpt_0")
        checkpoint_io.save(path, user_state())
        _, mgr = checkpoint_io.load(path, target=user_state(),
                                    device_put=False)
        assert mgr == {"step": 0, "batches_committed": 0}

    def test_device_put_restores_jax_arrays(self, tmp_path):
        path = str(tmp_path / "ckpt_1")
        checkpoint_io.save(path, user_state(3.0), {"step": 1,
                                                   "batches_committed": 1})
        user, _ = checkpoint_io.load(path, target=user_state())
        import jax

        assert isinstance(user["params"]["w"], jax.Array)
        np.testing.assert_array_equal(np.asarray(user["params"]["w"]),
                                      np.full((4, 4), 3.0))

    def test_makes_parent_dirs(self, tmp_path):
        path = str(tmp_path / "a" / "b" / "ckpt_2")
        checkpoint_io.save(path, user_state())
        assert os.path.exists(path)

    def test_structure_mismatch_fails_loudly(self, tmp_path):
        path = str(tmp_path / "ckpt_3")
        checkpoint_io.save(path, user_state())
        with pytest.raises(ValueError):
            checkpoint_io.load(path, target={"different": np.ones(2)},
                               device_put=False)


class TestAtomicity:
    def test_crash_mid_save_preserves_previous(self, tmp_path, monkeypatch):
        path = str(tmp_path / "ckpt_5")
        checkpoint_io.save(path, user_state(1.0), {"step": 5,
                                                   "batches_committed": 5})
        good = open(path, "rb").read()

        real_iter = checkpoint_io._iter_leaf_views

        def dies_midway(leaves, batch_bytes):
            it = real_iter(leaves, batch_bytes)
            yield next(it)
            raise OSError("disk died mid-write")

        monkeypatch.setattr(checkpoint_io, "_iter_leaf_views", dies_midway)
        with pytest.raises(OSError, match="disk died"):
            checkpoint_io.save(path, user_state(9.9), {"step": 6,
                                                       "batches_committed": 6})
        # Previous checkpoint is untouched and no temp junk is left behind.
        assert open(path, "rb").read() == good
        assert [n for n in os.listdir(tmp_path)
                if n.startswith(".ckpt_tmp_")] == []
        _, mgr = checkpoint_io.load(path, target=user_state(),
                                    device_put=False)
        assert mgr["step"] == 5


class TestLatest:
    def test_picks_highest_step(self, tmp_path):
        for step in (1, 12, 3):
            checkpoint_io.save(str(tmp_path / f"ckpt_{step}"), user_state())
        (tmp_path / "ckpt_notastep").write_bytes(b"x")
        (tmp_path / "unrelated").write_bytes(b"x")
        assert checkpoint_io.latest(str(tmp_path)) == str(tmp_path / "ckpt_12")

    def test_empty_and_missing_dir(self, tmp_path):
        assert checkpoint_io.latest(str(tmp_path)) is None
        assert checkpoint_io.latest(str(tmp_path / "nope")) is None

    def test_custom_prefix(self, tmp_path):
        checkpoint_io.save(str(tmp_path / "model_4"), user_state())
        assert checkpoint_io.latest(str(tmp_path), prefix="model_") == str(
            tmp_path / "model_4")
        assert checkpoint_io.latest(str(tmp_path)) is None


class TestAsyncCheckpointer:
    def test_matches_sync_save(self, tmp_path):
        from torchft_tpu.checkpoint_io import AsyncCheckpointer

        state = {"w": jnp.arange(6, dtype=jnp.float32), "n": np.int64(3)}
        mgr = {"step": 7, "batches_committed": 12}
        ck = AsyncCheckpointer()
        try:
            fut = ck.save_async(str(tmp_path / "ckpt_7"), state, mgr)
            assert fut.result(timeout=30) == str(tmp_path / "ckpt_7")
            user, m = checkpoint_io.load(str(tmp_path / "ckpt_7"), target=state,
                                device_put=False)
            np.testing.assert_array_equal(user["w"], np.arange(6))
            assert m == mgr
        finally:
            ck.shutdown()

    def test_snapshot_survives_mutation_after_call(self, tmp_path):
        """The on-device snapshot is taken at save_async time: replacing
        (or deleting) the caller's arrays afterwards must not change what
        lands on disk — the donation-safety contract."""
        from torchft_tpu.checkpoint_io import AsyncCheckpointer

        w = jnp.arange(4, dtype=jnp.float32)
        state = {"w": w}
        ck = AsyncCheckpointer()
        try:
            fut = ck.save_async(str(tmp_path / "ckpt_1"), state)
            w.delete()  # simulate a donated buffer being consumed
            fut.result(timeout=30)
            user, _ = checkpoint_io.load(str(tmp_path / "ckpt_1"),
                                target={"w": jnp.zeros(4)},
                                device_put=False)
            np.testing.assert_array_equal(user["w"], np.arange(4))
        finally:
            ck.shutdown()

    def test_serializes_overlapping_saves_and_prunes(self, tmp_path):
        from torchft_tpu.checkpoint_io import AsyncCheckpointer

        ck = AsyncCheckpointer(keep=2)
        try:
            for step in range(5):
                ck.save_async(str(tmp_path / f"ckpt_{step}"),
                              {"w": jnp.full(2, float(step))},
                              {"step": step, "batches_committed": step})
            ck.wait()
            names = sorted(p.name for p in tmp_path.iterdir()
                           if p.name.startswith("ckpt_"))
            assert names == ["ckpt_3", "ckpt_4"]
            assert checkpoint_io.latest(str(tmp_path)) == str(tmp_path / "ckpt_4")
        finally:
            ck.shutdown()

    def test_write_failure_surfaces_on_next_call(self, tmp_path):
        from torchft_tpu.checkpoint_io import AsyncCheckpointer

        ck = AsyncCheckpointer()
        try:
            bad = tmp_path / "noexist" / "sub"
            # Make the directory un-creatable by occupying the parent path
            # with a FILE.
            (tmp_path / "noexist").write_text("a file, not a dir")
            fut = ck.save_async(str(bad / "ckpt_1"), {"w": jnp.zeros(2)})
            with pytest.raises(Exception):
                fut.result(timeout=30)
            with pytest.raises(RuntimeError, match="previous async"):
                ck.save_async(str(tmp_path / "ckpt_2"), {"w": jnp.zeros(2)})
            # the latched error clears; subsequent saves work
            f2 = ck.save_async(str(tmp_path / "ckpt_3"), {"w": jnp.zeros(2)})
            assert f2.result(timeout=30)
        finally:
            ck.shutdown()
