"""Durable disk checkpoint tests: atomic write, crash-mid-save leaves the
previous checkpoint intact, latest() selection (verdict r1 #9 — this module
shipped untested in round 1)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from torchft_tpu import checkpoint_io


def user_state(val=1.0):
    return {
        "params": {"w": jnp.full((4, 4), val), "b": jnp.zeros((4,))},
        "opt": [jnp.ones((2,)), np.int64(3)],
    }


class TestSaveLoad:
    def test_round_trip_with_manager_state(self, tmp_path):
        path = str(tmp_path / "ckpt_7")
        checkpoint_io.save(path, user_state(2.5),
                           {"step": 7, "batches_committed": 21})
        user, mgr = checkpoint_io.load(path, target=user_state(),
                                       device_put=False)
        np.testing.assert_array_equal(user["params"]["w"],
                                      np.full((4, 4), 2.5))
        assert mgr == {"step": 7, "batches_committed": 21}

    def test_default_manager_state(self, tmp_path):
        path = str(tmp_path / "ckpt_0")
        checkpoint_io.save(path, user_state())
        _, mgr = checkpoint_io.load(path, target=user_state(),
                                    device_put=False)
        assert mgr == {"step": 0, "batches_committed": 0}

    def test_device_put_restores_jax_arrays(self, tmp_path):
        path = str(tmp_path / "ckpt_1")
        checkpoint_io.save(path, user_state(3.0), {"step": 1,
                                                   "batches_committed": 1})
        user, _ = checkpoint_io.load(path, target=user_state())
        import jax

        assert isinstance(user["params"]["w"], jax.Array)
        np.testing.assert_array_equal(np.asarray(user["params"]["w"]),
                                      np.full((4, 4), 3.0))

    def test_makes_parent_dirs(self, tmp_path):
        path = str(tmp_path / "a" / "b" / "ckpt_2")
        checkpoint_io.save(path, user_state())
        assert os.path.exists(path)

    def test_structure_mismatch_fails_loudly(self, tmp_path):
        path = str(tmp_path / "ckpt_3")
        checkpoint_io.save(path, user_state())
        with pytest.raises(ValueError):
            checkpoint_io.load(path, target={"different": np.ones(2)},
                               device_put=False)


class TestAtomicity:
    def test_crash_mid_save_preserves_previous(self, tmp_path, monkeypatch):
        path = str(tmp_path / "ckpt_5")
        checkpoint_io.save(path, user_state(1.0), {"step": 5,
                                                   "batches_committed": 5})
        good = open(path, "rb").read()

        real_iter = checkpoint_io.iter_pytree_chunks

        def dies_midway(tree):
            it = real_iter(tree)
            yield next(it)
            yield next(it)
            raise OSError("disk died mid-write")

        monkeypatch.setattr(checkpoint_io, "iter_pytree_chunks", dies_midway)
        with pytest.raises(OSError, match="disk died"):
            checkpoint_io.save(path, user_state(9.9), {"step": 6,
                                                       "batches_committed": 6})
        # Previous checkpoint is untouched and no temp junk is left behind.
        assert open(path, "rb").read() == good
        assert [n for n in os.listdir(tmp_path)
                if n.startswith(".ckpt_tmp_")] == []
        _, mgr = checkpoint_io.load(path, target=user_state(),
                                    device_put=False)
        assert mgr["step"] == 5


class TestLatest:
    def test_picks_highest_step(self, tmp_path):
        for step in (1, 12, 3):
            checkpoint_io.save(str(tmp_path / f"ckpt_{step}"), user_state())
        (tmp_path / "ckpt_notastep").write_bytes(b"x")
        (tmp_path / "unrelated").write_bytes(b"x")
        assert checkpoint_io.latest(str(tmp_path)) == str(tmp_path / "ckpt_12")

    def test_empty_and_missing_dir(self, tmp_path):
        assert checkpoint_io.latest(str(tmp_path)) is None
        assert checkpoint_io.latest(str(tmp_path / "nope")) is None

    def test_custom_prefix(self, tmp_path):
        checkpoint_io.save(str(tmp_path / "model_4"), user_state())
        assert checkpoint_io.latest(str(tmp_path), prefix="model_") == str(
            tmp_path / "model_4")
        assert checkpoint_io.latest(str(tmp_path)) is None
