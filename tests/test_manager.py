"""Manager state-machine unit tests.

Mirrors the reference's mocked-client test strategy
(/root/reference/torchft/manager_test.py): a real :class:`Manager` with the
native ``ManagerClient`` replaced by a mock and the communicator replaced by
:class:`DummyCommunicator`, making every protocol branch testable in one
process — happy path, sync/async healing, error latching + next-step
recovery, spares participation, and 1/n numerics.
"""

from unittest.mock import MagicMock, patch

import jax
import numpy as np
import pytest

from torchft_tpu._native import QuorumResult
from torchft_tpu.communicator import DummyCommunicator
from torchft_tpu.manager import Manager, WorldSizeMode


def quorum_result(
    quorum_id=1,
    recover_manager_address="manager:1234",
    store_address="store:1234",
    max_step=1,
    max_rank=0,
    max_world_size=2,
    replica_rank=0,
    replica_world_size=2,
    heal=False,
):
    return QuorumResult(
        quorum_id=quorum_id,
        recover_manager_address=recover_manager_address,
        store_address=store_address,
        max_step=max_step,
        max_rank=max_rank,
        max_world_size=max_world_size,
        replica_rank=replica_rank,
        replica_world_size=replica_world_size,
        heal=heal,
    )


def make_manager(client, comm=None, use_async_quorum=True,
                 min_replica_size=2, world_size_mode=WorldSizeMode.DYNAMIC,
                 load_state_dict=None, state_dict=None, **kwargs):
    return Manager(
        comm=comm or DummyCommunicator(),
        load_state_dict=load_state_dict or MagicMock(),
        state_dict=state_dict or (lambda: {"w": np.ones(2)}),
        min_replica_size=min_replica_size,
        use_async_quorum=use_async_quorum,
        world_size_mode=world_size_mode,
        rank=0,
        world_size=1,
        replica_id="testgroup",
        _manager_client=client,
        **kwargs,
    )


class TestManagerHappyPath:
    """reference manager_test.py:81-113"""

    def test_step_commit(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result(max_step=1)
        client.should_commit.return_value = True
        comm = DummyCommunicator()
        m = make_manager(client, comm)
        try:
            assert m.current_step() == 0
            m.step()
            fut = m.allreduce({"g": np.array([2.0, 4.0])})
            out = fut.result()
            # DummyCommunicator returns input unchanged; n=2 → halved.
            np.testing.assert_allclose(out["g"], [1.0, 2.0])
            assert m.should_commit()
            assert m.current_step() == 1
            assert m.num_participants() == 2
            assert comm.configure_count == 1  # quorum_id -1 → 1
            m.step()
            assert m.current_step() == 2
            assert m.batches_committed() == 2
            # same quorum id → no reconfigure
            assert comm.configure_count == 1
        finally:
            m.shutdown()

    def test_quorum_id_change_reconfigures(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result(quorum_id=1)
        client.should_commit.return_value = True
        comm = DummyCommunicator()
        m = make_manager(client, comm)
        try:
            m.step()
            m.should_commit()
            client.quorum.return_value = quorum_result(quorum_id=2)
            m.step()
            m.allreduce({"g": np.zeros(1)}).result()
            assert comm.configure_count == 2
        finally:
            m.shutdown()


class TestManagerHealing:
    """reference manager_test.py:116-257"""

    def _heal_quorum(self, max_step=20):
        return quorum_result(
            quorum_id=1, max_step=max_step, max_rank=None, max_world_size=1,
            replica_rank=1, replica_world_size=2, heal=True,
        )

    def _patch_heal(self, state):
        checkpoint = patch(
            "torchft_tpu.manager.CheckpointServer.load_from_address",
            return_value=state,
        )
        primary = patch("torchft_tpu.manager.ManagerClient")
        return checkpoint, primary

    def test_async_heal(self):
        client = MagicMock()
        client.quorum.return_value = self._heal_quorum(max_step=20)
        client.should_commit.return_value = True
        loaded = MagicMock()
        m = make_manager(client, use_async_quorum=True,
                         load_state_dict=loaded, min_replica_size=1)
        state = {"user": {"w": np.full(2, 7.0)},
                 "torchft": {"step": 20, "batches_committed": 40}}
        cp, pc = self._patch_heal(state)
        try:
            with cp, pc:
                m.step()
                # healer zeroes its contribution
                fut = m.allreduce({"g": np.array([8.0])})
                np.testing.assert_allclose(fut.result()["g"], [0.0])
                assert m.is_healing()
                assert not m.is_participating()
                assert m.num_participants() == 1
                assert m.should_commit()
            # user state applied on the main thread at commit
            loaded.assert_called_once()
            assert loaded.call_args[0][0] == state["user"]
            # manager metadata restored: step jumped to max_step
            assert m.current_step() == 20
            # next step participates normally
            client.quorum.return_value = quorum_result(
                quorum_id=1, max_step=21, max_rank=1, max_world_size=2,
                replica_rank=1, replica_world_size=2)
            m.step()
            m._quorum_future.result()  # deterministic: join quorum thread
            assert m.current_step() == 21
            assert not m.is_healing()
            assert m.is_participating()
        finally:
            m.shutdown()

    def test_sync_heal_participates_immediately(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result(
            quorum_id=1, max_step=5, max_rank=None, max_world_size=1,
            replica_rank=1, replica_world_size=2, heal=True)
        client.should_commit.return_value = True
        loaded = MagicMock()
        m = make_manager(client, use_async_quorum=False,
                         load_state_dict=loaded, min_replica_size=1)
        state = {"user": {"w": np.zeros(1)},
                 "torchft": {"step": 5, "batches_committed": 10}}
        cp, pc = self._patch_heal(state)
        try:
            with cp, pc:
                m.step()
            # sync mode: state restored before compute, participates now
            loaded.assert_called_once()
            assert m.is_participating()
            assert m.num_participants() == 2
            assert m.current_step() == 5
        finally:
            m.shutdown()

    def test_async_heal_too_few_participants_aborts_commit(self):
        client = MagicMock()
        client.quorum.return_value = self._heal_quorum()
        client.should_commit.return_value = False
        m = make_manager(client, min_replica_size=2)  # only 1 at max step
        state = {"user": {}, "torchft": {"step": 20, "batches_committed": 0}}
        cp, pc = self._patch_heal(state)
        try:
            with cp, pc:
                m.step()
                assert not m.should_commit()
            # local vote must have been False (not enough participants)
            assert client.should_commit.call_args.kwargs["should_commit"] is False
        finally:
            m.shutdown()


class TestManagerErrors:
    """reference manager_test.py:260-342"""

    def test_allreduce_error_latches_and_recovers(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.side_effect = [False, True]
        comm = DummyCommunicator()
        m = make_manager(client, comm)
        try:
            m.step()
            comm.allreduce = MagicMock(side_effect=RuntimeError("boom"))
            tree = {"g": np.array([3.0])}
            out = m.allreduce(tree).result()
            np.testing.assert_allclose(out["g"], [3.0])  # fallback: unchanged
            assert m.errored() is not None
            # further collectives no-op instantly
            out2 = m.allreduce({"g": np.array([5.0])}).result()
            np.testing.assert_allclose(out2["g"], [5.0])
            assert not m.should_commit()
            assert client.should_commit.call_args.kwargs["should_commit"] is False

            # next step: error cleared, step NOT bumped (no commit)
            comm.allreduce = DummyCommunicator.allreduce.__get__(comm)
            m.step()
            assert m.errored() is None
            assert m.current_step() == 1
            m.allreduce({"g": np.array([4.0])}).result()
            assert m.should_commit()
        finally:
            m.shutdown()

    def test_poisoned_future_swallowed(self):
        from concurrent.futures import Future

        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = False
        comm = DummyCommunicator()
        poisoned: Future = Future()
        poisoned.set_exception(RuntimeError("late failure"))
        comm.allreduce = MagicMock(return_value=poisoned)
        m = make_manager(client, comm)
        try:
            m.step()
            out = m.allreduce({"g": np.array([1.0, 2.0])}).result()
            np.testing.assert_allclose(out["g"], [1.0, 2.0])
            assert m.errored() is not None
            assert not m.should_commit()
        finally:
            m.shutdown()

    def test_quorum_error_latches(self):
        client = MagicMock()
        client.quorum.side_effect = RuntimeError("lighthouse down")
        client.should_commit.return_value = False
        m = make_manager(client)
        try:
            m.step()
            out = m.allreduce({"g": np.array([9.0])}).result()
            np.testing.assert_allclose(out["g"], [9.0])
            assert m.errored() is not None
        finally:
            m.shutdown()


class TestMetrics:
    """Observability surface beyond the reference's
    current_step/batches_committed (manager.py:484-506)."""

    def test_counters_and_timings_update(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result(max_step=1)
        client.should_commit.return_value = True
        m = make_manager(client)
        try:
            m.step()
            m.allreduce({"g": np.array([2.0, 4.0])}).result()
            assert m.should_commit()
            metrics = m.metrics()
            assert metrics["quorum_count"] == 1
            assert metrics["quorum_ms_total"] >= 0.0
            assert metrics["reconfigure_count"] == 1  # quorum_id -1 -> 1
            assert metrics["allreduce_count"] == 1
            assert metrics["commit_count"] == 1
            assert metrics["committed_steps"] == 1
            assert metrics["aborted_steps"] == 0
            assert metrics["heal_count"] == 0
        finally:
            m.shutdown()

    def test_aborted_step_counted(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result(max_step=1)
        client.should_commit.return_value = False
        m = make_manager(client)
        try:
            m.step()
            assert not m.should_commit()
            metrics = m.metrics()
            assert metrics["aborted_steps"] == 1
            assert metrics["committed_steps"] == 0
        finally:
            m.shutdown()


class TestFailFast:
    """Persistent control-plane failure must surface to the caller instead
    of livelocking the training loop (round-1 VERDICT weak #8)."""

    def test_raises_after_consecutive_quorum_failures(self):
        client = MagicMock()
        client.quorum.side_effect = RuntimeError("lighthouse down")
        client.should_commit.return_value = False
        m = Manager(
            comm=DummyCommunicator(),
            load_state_dict=MagicMock(),
            state_dict=lambda: {},
            min_replica_size=2,
            rank=0,
            world_size=1,
            replica_id="testgroup",
            max_consecutive_failures=3,
            _manager_client=client,
        )
        try:
            with pytest.raises(RuntimeError, match="consecutive quorum"):
                for _ in range(10):
                    m.step()
                    assert not m.should_commit()
            # It took exactly max_consecutive_failures failed rounds.
            assert client.quorum.call_count == 3
        finally:
            m.shutdown()

    def test_streak_resets_on_success(self):
        client = MagicMock()
        client.quorum.side_effect = [
            RuntimeError("blip"),
            quorum_result(max_step=1),
            quorum_result(max_step=2),
        ]
        client.should_commit.side_effect = [False, True, True]
        m = Manager(
            comm=DummyCommunicator(),
            load_state_dict=MagicMock(),
            state_dict=lambda: {},
            min_replica_size=2,
            rank=0,
            world_size=1,
            replica_id="testgroup",
            max_consecutive_failures=2,
            _manager_client=client,
        )
        try:
            m.step()
            assert not m.should_commit()
            m.step()  # succeeds, resets the streak
            assert m.should_commit()
            m.step()  # must NOT raise even though one failure happened
            assert m.should_commit()
        finally:
            m.shutdown()


class TestSpares:
    """reference manager_test.py:345-379"""

    def test_spare_is_benched(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result(
            max_rank=2, max_world_size=3, replica_rank=2,
            replica_world_size=3)
        client.should_commit.return_value = True
        m = make_manager(client, min_replica_size=2,
                         world_size_mode=WorldSizeMode.FIXED_WITH_SPARES)
        try:
            m.step()
            out = m.allreduce({"g": np.array([6.0])}).result()
            # benched: zero contribution, world clamped to 2 → 0/2
            np.testing.assert_allclose(out["g"], [0.0])
            assert not m.is_participating()
            assert m.num_participants() == 2
        finally:
            m.shutdown()

    def test_non_spare_clamped_world(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result(
            max_rank=1, max_world_size=3, replica_rank=1,
            replica_world_size=3)
        client.should_commit.return_value = True
        m = make_manager(client, min_replica_size=2,
                         world_size_mode=WorldSizeMode.FIXED_WITH_SPARES)
        try:
            m.step()
            out = m.allreduce({"g": np.array([6.0])}).result()
            np.testing.assert_allclose(out["g"], [3.0])  # 1/2 not 1/3
            assert m.is_participating()
        finally:
            m.shutdown()


class TestNumerics:
    """reference manager_test.py:405-427"""

    @pytest.mark.parametrize("world", [1, 2, 4, 7])
    def test_one_over_n(self, world):
        client = MagicMock()
        client.quorum.return_value = quorum_result(
            max_rank=0, max_world_size=world, replica_rank=0,
            replica_world_size=world)
        client.should_commit.return_value = True
        m = make_manager(client, min_replica_size=1)
        try:
            m.step()
            out = m.allreduce({"g": np.full(3, float(world))}).result()
            np.testing.assert_allclose(out["g"], np.ones(3))
        finally:
            m.shutdown()

    def test_int_grads_floor_divide(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = True
        m = make_manager(client)
        try:
            m.step()
            out = m.allreduce({"g": np.array([5], dtype=np.int64)}).result()
            assert out["g"][0] == 2
        finally:
            m.shutdown()

    @pytest.mark.parametrize("bucket_bytes", [1, 64, 1 << 20])
    def test_bucketed_matches_single(self, bucket_bytes):
        """The pipelined bucketed host allreduce is numerically identical
        to the single-shot path at world=2, where two-term sums are
        order-insensitive (at world>=3 ring chunk boundaries shift with
        bucketing, allowing last-ulp reorder differences — see
        _host_allreduce_pipelined's docstring). bucket_bytes=1 forces one
        bucket per leaf; 1MB collapses to a single bucket (the old
        behavior). Cross-rank bitwise agreement is asserted at any world
        by comparing both ranks' results below."""
        import threading as _t

        from torchft_tpu._native import Store
        from torchft_tpu.backends.host import HostCommunicator

        store = Store(bind="127.0.0.1:0")
        world = 2
        rng = np.random.default_rng(0)
        tree = {
            "a": rng.normal(size=(17, 3)).astype(np.float32),
            "b": rng.normal(size=(130,)).astype(np.float32),
            "c": {"d": rng.normal(size=(5,)).astype(np.float64),
                  "e": np.arange(6, dtype=np.int64)},
        }
        expected = {  # mean of (tree, 2*tree) = 1.5*tree; int floor-divides
            "a": tree["a"] * 1.5,
            "b": tree["b"] * 1.5,
            "c": {"d": tree["c"]["d"] * 1.5,
                  "e": (tree["c"]["e"] * 3) // 2},
        }
        results = [None] * world
        errors = []

        def run(rank):
            client = MagicMock()
            client.quorum.return_value = quorum_result(
                store_address=store.address(),
                max_rank=rank, max_world_size=world,
                replica_rank=rank, replica_world_size=world)
            client.should_commit.return_value = True
            m = make_manager(
                client, comm=HostCommunicator(timeout_sec=30),
                allreduce_bucket_bytes=bucket_bytes)
            try:
                m.step()
                scaled = jax.tree_util.tree_map(
                    lambda a: a * (rank + 1), tree)
                results[rank] = m.allreduce(scaled).result(timeout=30)
                assert m.should_commit()
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                m.shutdown()

        threads = [_t.Thread(target=run, args=(r,)) for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        alive = [t for t in threads if t.is_alive()]
        store.shutdown()
        assert not alive, "pipelined allreduce deadlocked"
        assert not errors, errors
        for out in results:
            assert out is not None, "worker produced no result"
            flat_out = jax.tree_util.tree_leaves(out)
            flat_exp = jax.tree_util.tree_leaves(expected)
            assert len(flat_out) == len(flat_exp)
            for o, e in zip(flat_out, flat_exp):
                np.testing.assert_array_equal(np.asarray(o), e)

    def test_zero_element_leaf_and_host_precision_under_wire(self):
        """Two packing edge cases: (1) a 0-element leaf must contribute 0
        to the packed payload geometry (an off-by-one would wedge the
        ring / break the split); (2) host-native float leaves never cross
        the D2H link, so wire compression must NOT quantize them — their
        averaged values stay bitwise full-precision."""
        import threading as _t

        import jax.numpy as jnp

        from torchft_tpu._native import Store
        from torchft_tpu.backends.host import HostCommunicator

        store = Store(bind="127.0.0.1:0")
        world = 2
        rng = np.random.default_rng(2)
        host_leaf = rng.normal(size=(33,)).astype(np.float32)
        tree = {
            "empty": np.zeros((0, 5), np.float32),
            "host": host_leaf,                      # numpy: stays exact
            "dev": jnp.asarray(rng.normal(size=(40,)).astype(np.float32)),
        }
        results = [None] * world
        errors = []

        def run(rank):
            client = MagicMock()
            client.quorum.return_value = quorum_result(
                store_address=store.address(),
                max_rank=rank, max_world_size=world,
                replica_rank=rank, replica_world_size=world)
            client.should_commit.return_value = True
            m = make_manager(
                client, comm=HostCommunicator(timeout_sec=30),
                allreduce_bucket_bytes=64,  # force multi-bucket
                allreduce_wire_dtype=jnp.bfloat16)
            try:
                m.step()
                scaled = jax.tree_util.tree_map(
                    lambda a: a * (rank + 1), tree)
                results[rank] = m.allreduce(scaled).result(timeout=30)
                assert m.should_commit()
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                m.shutdown()

        threads = [_t.Thread(target=run, args=(r,)) for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        alive = [t for t in threads if t.is_alive()]
        store.shutdown()
        assert not alive, "packed allreduce deadlocked on empty leaf"
        assert not errors, errors
        for out in results:
            assert out["empty"].shape == (0, 5)
            # Host-native leaf: exact mean, no bf16 quantization anywhere.
            np.testing.assert_array_equal(
                np.asarray(out["host"]), host_leaf * 1.5)

    def test_bf16_wire_compression_close_to_exact(self):
        """allreduce_wire_dtype=bfloat16 quantizes each local contribution
        once; the sum/scale stay f32, so the result tracks the exact mean
        within bf16 rounding (~3 decimal digits)."""
        import threading as _t

        import jax.numpy as jnp

        from torchft_tpu._native import Store
        from torchft_tpu.backends.host import HostCommunicator

        store = Store(bind="127.0.0.1:0")
        world = 2
        rng = np.random.default_rng(1)
        base = rng.normal(size=(257,)).astype(np.float32)
        results = [None] * world
        errors = []

        def run(rank):
            client = MagicMock()
            client.quorum.return_value = quorum_result(
                store_address=store.address(),
                max_rank=rank, max_world_size=world,
                replica_rank=rank, replica_world_size=world)
            client.should_commit.return_value = True
            m = Manager(
                comm=HostCommunicator(timeout_sec=30),
                load_state_dict=MagicMock(),
                state_dict=lambda: {},
                min_replica_size=2, rank=0, world_size=1,
                replica_id=f"wire{rank}",
                allreduce_wire_dtype=jnp.bfloat16,
                _manager_client=client,
            )
            try:
                m.step()
                tree = {"g": jnp.asarray(base * (rank + 1))}
                results[rank] = m.allreduce(tree).result(timeout=30)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                m.shutdown()

        threads = [_t.Thread(target=run, args=(r,)) for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        alive = [t for t in threads if t.is_alive()]
        store.shutdown()
        assert not alive, "wire-compressed allreduce deadlocked"
        assert not errors, errors
        for out in results:
            assert out is not None, "worker produced no result"
            # Callers must get their original dtype back, not the wire one.
            assert np.dtype(out["g"].dtype) == np.float32
            got = np.asarray(out["g"])
            np.testing.assert_allclose(got, base * 1.5, rtol=1e-2, atol=1e-2)

    def test_state_dict_roundtrip(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = True
        m = make_manager(client)
        try:
            m.step()
            m.should_commit()
            sd = m.state_dict()
            assert sd == {"step": 1, "batches_committed": 0}
            m.load_state_dict({"step": 42, "batches_committed": 84})
            assert m.current_step() == 42
            assert m.batches_committed() == 84
        finally:
            m.shutdown()
