"""Manager state-machine unit tests.

Mirrors the reference's mocked-client test strategy
(/root/reference/torchft/manager_test.py): a real :class:`Manager` with the
native ``ManagerClient`` replaced by a mock and the communicator replaced by
:class:`DummyCommunicator`, making every protocol branch testable in one
process — happy path, sync/async healing, error latching + next-step
recovery, spares participation, and 1/n numerics.
"""

from unittest.mock import MagicMock, patch

import jax
import numpy as np
import pytest

import conftest
from torchft_tpu._native import QuorumResult
from torchft_tpu.communicator import DummyCommunicator
from torchft_tpu.manager import Manager, WorldSizeMode, _derive_schedule

requires_native = conftest.requires_native()


def quorum_result(
    quorum_id=1,
    recover_manager_address="manager:1234",
    store_address="store:1234",
    max_step=1,
    max_rank=0,
    max_world_size=2,
    replica_rank=0,
    replica_world_size=2,
    heal=False,
):
    return QuorumResult(
        quorum_id=quorum_id,
        recover_manager_address=recover_manager_address,
        store_address=store_address,
        max_step=max_step,
        max_rank=max_rank,
        max_world_size=max_world_size,
        replica_rank=replica_rank,
        replica_world_size=replica_world_size,
        heal=heal,
    )


def make_manager(client, comm=None, use_async_quorum=True,
                 min_replica_size=2, world_size_mode=WorldSizeMode.DYNAMIC,
                 load_state_dict=None, state_dict=None, **kwargs):
    return Manager(
        comm=comm or DummyCommunicator(),
        load_state_dict=load_state_dict or MagicMock(),
        state_dict=state_dict or (lambda: {"w": np.ones(2)}),
        min_replica_size=min_replica_size,
        use_async_quorum=use_async_quorum,
        world_size_mode=world_size_mode,
        rank=0,
        world_size=1,
        replica_id="testgroup",
        _manager_client=client,
        **kwargs,
    )


class TestManagerHappyPath:
    """reference manager_test.py:81-113"""

    def test_step_commit(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result(max_step=1)
        client.should_commit.return_value = True
        comm = DummyCommunicator()
        m = make_manager(client, comm)
        try:
            assert m.current_step() == 0
            m.step()
            fut = m.allreduce({"g": np.array([2.0, 4.0])})
            out = fut.result()
            # DummyCommunicator returns input unchanged; n=2 → halved.
            np.testing.assert_allclose(out["g"], [1.0, 2.0])
            assert m.should_commit()
            assert m.current_step() == 1
            assert m.num_participants() == 2
            assert comm.configure_count == 1  # quorum_id -1 → 1
            m.step()
            assert m.current_step() == 2
            assert m.batches_committed() == 2
            # same quorum id → no reconfigure
            assert comm.configure_count == 1
        finally:
            m.shutdown()

    def test_quorum_id_change_reconfigures(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result(quorum_id=1)
        client.should_commit.return_value = True
        comm = DummyCommunicator()
        m = make_manager(client, comm)
        try:
            m.step()
            m.should_commit()
            client.quorum.return_value = quorum_result(quorum_id=2)
            m.step()
            m.allreduce({"g": np.zeros(1)}).result()
            assert comm.configure_count == 2
        finally:
            m.shutdown()


class TestManagerHealing:
    """reference manager_test.py:116-257"""

    def _heal_quorum(self, max_step=20):
        return quorum_result(
            quorum_id=1, max_step=max_step, max_rank=None, max_world_size=1,
            replica_rank=1, replica_world_size=2, heal=True,
        )

    def _patch_heal(self, state):
        checkpoint = patch(
            "torchft_tpu.manager.CheckpointServer.load_from_address",
            return_value=state,
        )
        primary = patch("torchft_tpu.manager.ManagerClient")
        return checkpoint, primary

    def test_async_heal(self):
        client = MagicMock()
        client.quorum.return_value = self._heal_quorum(max_step=20)
        client.should_commit.return_value = True
        loaded = MagicMock()
        m = make_manager(client, use_async_quorum=True,
                         load_state_dict=loaded, min_replica_size=1)
        state = {"user": {"w": np.full(2, 7.0)},
                 "torchft": {"step": 20, "batches_committed": 40}}
        cp, pc = self._patch_heal(state)
        try:
            with cp, pc:
                m.step()
                # healer zeroes its contribution
                fut = m.allreduce({"g": np.array([8.0])})
                np.testing.assert_allclose(fut.result()["g"], [0.0])
                assert m.is_healing()
                assert not m.is_participating()
                assert m.num_participants() == 1
                assert m.should_commit()
            # user state applied on the main thread at commit
            loaded.assert_called_once()
            assert loaded.call_args[0][0] == state["user"]
            # manager metadata restored: step jumped to max_step
            assert m.current_step() == 20
            # next step participates normally
            client.quorum.return_value = quorum_result(
                quorum_id=1, max_step=21, max_rank=1, max_world_size=2,
                replica_rank=1, replica_world_size=2)
            m.step()
            m._quorum_future.result()  # deterministic: join quorum thread
            assert m.current_step() == 21
            assert not m.is_healing()
            assert m.is_participating()
        finally:
            m.shutdown()

    def test_sync_heal_participates_immediately(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result(
            quorum_id=1, max_step=5, max_rank=None, max_world_size=1,
            replica_rank=1, replica_world_size=2, heal=True)
        client.should_commit.return_value = True
        loaded = MagicMock()
        m = make_manager(client, use_async_quorum=False,
                         load_state_dict=loaded, min_replica_size=1)
        state = {"user": {"w": np.zeros(1)},
                 "torchft": {"step": 5, "batches_committed": 10}}
        cp, pc = self._patch_heal(state)
        try:
            with cp, pc:
                m.step()
            # sync mode: state restored before compute, participates now
            loaded.assert_called_once()
            assert m.is_participating()
            assert m.num_participants() == 2
            assert m.current_step() == 5
        finally:
            m.shutdown()

    def test_async_heal_too_few_participants_aborts_commit(self):
        client = MagicMock()
        client.quorum.return_value = self._heal_quorum()
        client.should_commit.return_value = False
        m = make_manager(client, min_replica_size=2)  # only 1 at max step
        state = {"user": {}, "torchft": {"step": 20, "batches_committed": 0}}
        cp, pc = self._patch_heal(state)
        try:
            with cp, pc:
                m.step()
                assert not m.should_commit()
            # local vote must have been False (not enough participants)
            assert client.should_commit.call_args.kwargs["should_commit"] is False
        finally:
            m.shutdown()


class TestManagerHealFailover:
    """ISSUE 3 acceptance, manager level, pure Python (no native lib):
    the donor dies at >=50% heal-transfer progress; the Manager
    re-resolves a fresh donor via re-quorum and the SAME resumable
    transfer completes from the second donor with bitwise-identical
    state, re-sending strictly less than the full payload."""

    def test_donor_death_mid_heal_fails_over_via_requorum(self):
        import urllib.parse

        from torchft_tpu import chaos as chaos_mod
        from torchft_tpu.chaos import ChaosSchedule, EndpointChaos
        from torchft_tpu.checkpointing import CheckpointServer
        from torchft_tpu.serialization import plan_pytree

        rng = np.random.RandomState(3)
        user_state = {f"w{i}": rng.rand(4096).astype(np.float32)
                      for i in range(8)}
        donor_state = {"user": user_state,
                       "torchft": {"step": 20, "batches_committed": 40}}
        donor_a = CheckpointServer(lambda: donor_state,
                                   bind_host="127.0.0.1")
        donor_b = CheckpointServer(lambda: donor_state,
                                   bind_host="127.0.0.1")
        donor_a.allow_checkpoint(20)
        donor_b.allow_checkpoint(20)
        payload = plan_pytree(donor_state).total_len
        netloc_a = urllib.parse.urlparse(donor_a.address()).netloc
        # donor A's stream dies deterministically at ~60% of the payload
        chaos_mod.install(ChaosSchedule(seed=0, endpoints={
            f"heal:{netloc_a}": EndpointChaos(
                kill_after_bytes=int(payload * 0.6)),
        }))

        def heal_quorum(recover):
            return quorum_result(
                quorum_id=1, max_step=20, max_rank=None, max_world_size=1,
                replica_rank=1, replica_world_size=2, heal=True,
                recover_manager_address=recover)

        client = MagicMock()
        # initial quorum names donor A; the mid-heal re-quorum (after A's
        # death) names donor B
        client.quorum.side_effect = [heal_quorum("managerA"),
                                     heal_quorum("managerB")]
        client.should_commit.return_value = True
        ckpt_addrs = {"managerA": donor_a.address(),
                      "managerB": donor_b.address()}

        def make_client(addr, **kwargs):
            mc = MagicMock()
            mc.checkpoint_address.return_value = ckpt_addrs[addr]
            return mc

        loaded = MagicMock()
        pc = patch("torchft_tpu.manager.ManagerClient",
                   side_effect=make_client)
        m = make_manager(
            client, use_async_quorum=True, load_state_dict=loaded,
            min_replica_size=1,
            state_dict=lambda: {f"w{i}": np.zeros(4096, np.float32)
                                for i in range(8)})
        try:
            with pc:
                m.step()
                assert m.should_commit()
        finally:
            m.shutdown()
            chaos_mod.uninstall()
            donor_a.shutdown()
            donor_b.shutdown()

        # healed user state applied at commit, bitwise identical
        loaded.assert_called_once()
        healed = loaded.call_args[0][0]
        for key, arr in user_state.items():
            assert healed[key].tobytes() == arr.tobytes()
        assert m.current_step() == 20  # manager metadata restored

        mx = m.metrics()
        assert mx["heal_count"] == 1
        assert mx["heal_donor_failovers"] == 1
        assert mx["heal_attempts_total"] >= 2
        # the resumed leg re-sent strictly less than the full payload
        assert 0 < mx["heal_bytes_resumed_total"] < payload
        # >=50% of the transfer survived the donor's death
        assert mx["heal_bytes_resumed_total"] <= payload * 0.5
        assert mx["heal_bytes_total"] > 0
        # live progress gauge landed on a completed transfer
        assert mx["heal_last_payload_bytes"] == payload
        assert mx["heal_last_bytes_committed"] > 0
        # both quorum joins happened (initial + mid-heal re-resolution)
        assert client.quorum.call_count == 2
        events = [e["event"] for e in m.history()]
        assert "heal_failover" in events
        assert "heal" in events

    def test_requorum_moved_on_aborts_failover(self):
        """When the mid-heal re-quorum no longer heals at the same
        max_step (the world moved on), the failover is abandoned and the
        heal fails cleanly — the next step starts a fresh heal."""
        import urllib.parse

        from torchft_tpu import chaos as chaos_mod
        from torchft_tpu.chaos import ChaosSchedule, EndpointChaos
        from torchft_tpu.checkpointing import CheckpointServer
        from torchft_tpu.serialization import plan_pytree

        user_state = {"w": np.arange(8192, dtype=np.float32)}
        donor_state = {"user": user_state,
                       "torchft": {"step": 20, "batches_committed": 40}}
        donor_a = CheckpointServer(lambda: donor_state,
                                   bind_host="127.0.0.1")
        donor_a.allow_checkpoint(20)
        payload = plan_pytree(donor_state).total_len
        netloc_a = urllib.parse.urlparse(donor_a.address()).netloc
        chaos_mod.install(ChaosSchedule(seed=0, endpoints={
            f"heal:{netloc_a}": EndpointChaos(
                kill_after_bytes=int(payload * 0.5)),
        }))

        client = MagicMock()
        client.quorum.side_effect = [
            quorum_result(quorum_id=1, max_step=20, max_rank=None,
                          max_world_size=1, replica_rank=1,
                          replica_world_size=2, heal=True,
                          recover_manager_address="managerA"),
            # re-quorum: everyone advanced, no heal offered at step 20
            quorum_result(quorum_id=1, max_step=25, max_rank=1,
                          max_world_size=2, replica_rank=1,
                          replica_world_size=2, heal=False),
        ]
        client.should_commit.return_value = False
        loaded = MagicMock()

        def make_client(addr, **kwargs):
            mc = MagicMock()
            mc.checkpoint_address.return_value = donor_a.address()
            return mc

        m = make_manager(
            client, use_async_quorum=True, load_state_dict=loaded,
            min_replica_size=1,
            state_dict=lambda: {"w": np.zeros(8192, np.float32)})
        try:
            with patch("torchft_tpu.manager.ManagerClient",
                       side_effect=make_client):
                m.step()
                # heal failed (donor dead, no replacement): the step
                # aborts instead of wedging
                assert not m.should_commit()
        finally:
            m.shutdown()
            chaos_mod.uninstall()
            donor_a.shutdown()
        loaded.assert_not_called()
        assert m.errored() is not None
        mx = m.metrics()
        assert mx["heal_donor_failovers"] == 0
        assert mx["heal_count"] == 1
        # failed heals still record their wire cost + attempt history
        assert mx["heal_attempts_total"] >= 1
        assert mx["heal_bytes_total"] > 0


class TestManagerErrors:
    """reference manager_test.py:260-342"""

    def test_allreduce_error_latches_and_recovers(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.side_effect = [False, True]
        comm = DummyCommunicator()
        m = make_manager(client, comm)
        try:
            m.step()
            comm.allreduce = MagicMock(side_effect=RuntimeError("boom"))
            tree = {"g": np.array([3.0])}
            out = m.allreduce(tree).result()
            np.testing.assert_allclose(out["g"], [3.0])  # fallback: unchanged
            assert m.errored() is not None
            # further collectives no-op instantly
            out2 = m.allreduce({"g": np.array([5.0])}).result()
            np.testing.assert_allclose(out2["g"], [5.0])
            assert not m.should_commit()
            assert client.should_commit.call_args.kwargs["should_commit"] is False

            # next step: error cleared, step NOT bumped (no commit)
            comm.allreduce = DummyCommunicator.allreduce.__get__(comm)
            m.step()
            assert m.errored() is None
            assert m.current_step() == 1
            m.allreduce({"g": np.array([4.0])}).result()
            assert m.should_commit()
        finally:
            m.shutdown()

    def test_poisoned_future_swallowed(self):
        from concurrent.futures import Future

        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = False
        comm = DummyCommunicator()
        poisoned: Future = Future()
        poisoned.set_exception(RuntimeError("late failure"))
        comm.allreduce = MagicMock(return_value=poisoned)
        m = make_manager(client, comm)
        try:
            m.step()
            out = m.allreduce({"g": np.array([1.0, 2.0])}).result()
            np.testing.assert_allclose(out["g"], [1.0, 2.0])
            assert m.errored() is not None
            assert not m.should_commit()
        finally:
            m.shutdown()

    def test_quorum_error_latches(self):
        client = MagicMock()
        client.quorum.side_effect = RuntimeError("lighthouse down")
        client.should_commit.return_value = False
        m = make_manager(client)
        try:
            m.step()
            out = m.allreduce({"g": np.array([9.0])}).result()
            np.testing.assert_allclose(out["g"], [9.0])
            assert m.errored() is not None
        finally:
            m.shutdown()


class TestMetrics:
    """Observability surface beyond the reference's
    current_step/batches_committed (manager.py:484-506)."""

    def test_counters_and_timings_update(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result(max_step=1)
        client.should_commit.return_value = True
        m = make_manager(client)
        try:
            m.step()
            m.allreduce({"g": np.array([2.0, 4.0])}).result()
            assert m.should_commit()
            metrics = m.metrics()
            assert metrics["quorum_count"] == 1
            assert metrics["quorum_ms_total"] >= 0.0
            assert metrics["reconfigure_count"] == 1  # quorum_id -1 -> 1
            assert metrics["allreduce_count"] == 1
            assert metrics["commit_count"] == 1
            assert metrics["committed_steps"] == 1
            assert metrics["aborted_steps"] == 0
            assert metrics["heal_count"] == 0
        finally:
            m.shutdown()

    def test_aborted_step_counted(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result(max_step=1)
        client.should_commit.return_value = False
        m = make_manager(client)
        try:
            m.step()
            assert not m.should_commit()
            metrics = m.metrics()
            assert metrics["aborted_steps"] == 1
            assert metrics["committed_steps"] == 0
        finally:
            m.shutdown()


class TestFailFast:
    """Persistent control-plane failure must surface to the caller instead
    of livelocking the training loop (round-1 VERDICT weak #8)."""

    def test_raises_after_consecutive_quorum_failures(self):
        client = MagicMock()
        client.quorum.side_effect = RuntimeError("lighthouse down")
        client.should_commit.return_value = False
        m = Manager(
            comm=DummyCommunicator(),
            load_state_dict=MagicMock(),
            state_dict=lambda: {},
            min_replica_size=2,
            rank=0,
            world_size=1,
            replica_id="testgroup",
            max_consecutive_failures=3,
            _manager_client=client,
        )
        try:
            with pytest.raises(RuntimeError, match="consecutive quorum"):
                for _ in range(10):
                    m.step()
                    assert not m.should_commit()
            # It took exactly max_consecutive_failures failed rounds.
            assert client.quorum.call_count == 3
        finally:
            m.shutdown()

    def test_streak_resets_on_success(self):
        client = MagicMock()
        client.quorum.side_effect = [
            RuntimeError("blip"),
            quorum_result(max_step=1),
            quorum_result(max_step=2),
        ]
        client.should_commit.side_effect = [False, True, True]
        m = Manager(
            comm=DummyCommunicator(),
            load_state_dict=MagicMock(),
            state_dict=lambda: {},
            min_replica_size=2,
            rank=0,
            world_size=1,
            replica_id="testgroup",
            max_consecutive_failures=2,
            _manager_client=client,
        )
        try:
            m.step()
            assert not m.should_commit()
            m.step()  # succeeds, resets the streak
            assert m.should_commit()
            m.step()  # must NOT raise even though one failure happened
            assert m.should_commit()
        finally:
            m.shutdown()


class TestSpares:
    """reference manager_test.py:345-379"""

    def test_spare_is_benched(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result(
            max_rank=2, max_world_size=3, replica_rank=2,
            replica_world_size=3)
        client.should_commit.return_value = True
        m = make_manager(client, min_replica_size=2,
                         world_size_mode=WorldSizeMode.FIXED_WITH_SPARES)
        try:
            m.step()
            out = m.allreduce({"g": np.array([6.0])}).result()
            # benched: zero contribution, world clamped to 2 → 0/2
            np.testing.assert_allclose(out["g"], [0.0])
            assert not m.is_participating()
            assert m.num_participants() == 2
        finally:
            m.shutdown()

    def test_non_spare_clamped_world(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result(
            max_rank=1, max_world_size=3, replica_rank=1,
            replica_world_size=3)
        client.should_commit.return_value = True
        m = make_manager(client, min_replica_size=2,
                         world_size_mode=WorldSizeMode.FIXED_WITH_SPARES)
        try:
            m.step()
            out = m.allreduce({"g": np.array([6.0])}).result()
            np.testing.assert_allclose(out["g"], [3.0])  # 1/2 not 1/3
            assert m.is_participating()
        finally:
            m.shutdown()


class TestNumerics:
    """reference manager_test.py:405-427"""

    @pytest.mark.parametrize("world", [1, 2, 4, 7])
    def test_one_over_n(self, world):
        client = MagicMock()
        client.quorum.return_value = quorum_result(
            max_rank=0, max_world_size=world, replica_rank=0,
            replica_world_size=world)
        client.should_commit.return_value = True
        m = make_manager(client, min_replica_size=1)
        try:
            m.step()
            out = m.allreduce({"g": np.full(3, float(world))}).result()
            np.testing.assert_allclose(out["g"], np.ones(3))
        finally:
            m.shutdown()

    def test_int_grads_floor_divide(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = True
        m = make_manager(client)
        try:
            m.step()
            out = m.allreduce({"g": np.array([5], dtype=np.int64)}).result()
            assert out["g"][0] == 2
        finally:
            m.shutdown()

    @requires_native
    @pytest.mark.parametrize("bucket_bytes", [1, 64, 1 << 20])
    def test_bucketed_matches_single(self, bucket_bytes):
        """The pipelined bucketed host allreduce is numerically identical
        to the single-shot path at world=2, where two-term sums are
        order-insensitive (at world>=3 ring chunk boundaries shift with
        bucketing, allowing last-ulp reorder differences — see
        _host_allreduce_pipelined's docstring). bucket_bytes=1 forces one
        bucket per leaf; 1MB collapses to a single bucket (the old
        behavior). Cross-rank bitwise agreement is asserted at any world
        by comparing both ranks' results below."""
        import threading as _t

        from torchft_tpu._native import Store
        from torchft_tpu.backends.host import HostCommunicator

        store = Store(bind="127.0.0.1:0")
        world = 2
        rng = np.random.default_rng(0)
        tree = {
            "a": rng.normal(size=(17, 3)).astype(np.float32),
            "b": rng.normal(size=(130,)).astype(np.float32),
            "c": {"d": rng.normal(size=(5,)).astype(np.float64),
                  "e": np.arange(6, dtype=np.int64)},
        }
        expected = {  # mean of (tree, 2*tree) = 1.5*tree; int floor-divides
            "a": tree["a"] * 1.5,
            "b": tree["b"] * 1.5,
            "c": {"d": tree["c"]["d"] * 1.5,
                  "e": (tree["c"]["e"] * 3) // 2},
        }
        results = [None] * world
        errors = []

        def run(rank):
            client = MagicMock()
            client.quorum.return_value = quorum_result(
                store_address=store.address(),
                max_rank=rank, max_world_size=world,
                replica_rank=rank, replica_world_size=world)
            client.should_commit.return_value = True
            m = make_manager(
                client, comm=HostCommunicator(timeout_sec=30),
                allreduce_bucket_bytes=bucket_bytes)
            try:
                m.step()
                scaled = jax.tree_util.tree_map(
                    lambda a: a * (rank + 1), tree)
                results[rank] = m.allreduce(scaled).result(timeout=30)
                assert m.should_commit()
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                m.shutdown()

        threads = [_t.Thread(target=run, args=(r,)) for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        alive = [t for t in threads if t.is_alive()]
        store.shutdown()
        assert not alive, "pipelined allreduce deadlocked"
        assert not errors, errors
        for out in results:
            assert out is not None, "worker produced no result"
            flat_out = jax.tree_util.tree_leaves(out)
            flat_exp = jax.tree_util.tree_leaves(expected)
            assert len(flat_out) == len(flat_exp)
            for o, e in zip(flat_out, flat_exp):
                np.testing.assert_array_equal(np.asarray(o), e)

    @requires_native
    def test_zero_element_leaf_and_host_leaves_under_wire(self):
        """Two packing edge cases: (1) a 0-element leaf must contribute 0
        to the packed payload geometry (an off-by-one would wedge the
        ring / break the split); (2) the wire dtype is END-TO-END (the
        TCP ring carries it too, not just the D2H leg), so host-native
        float leaves are quantized exactly once like every other
        contribution — bounded by one bf16 quantization each, and
        bitwise identical across ranks."""
        import threading as _t

        import jax.numpy as jnp

        from torchft_tpu._native import Store
        from torchft_tpu.backends.host import HostCommunicator

        store = Store(bind="127.0.0.1:0")
        world = 2
        rng = np.random.default_rng(2)
        host_leaf = rng.normal(size=(33,)).astype(np.float32)
        tree = {
            "empty": np.zeros((0, 5), np.float32),
            "host": host_leaf,                      # numpy: stays exact
            "dev": jnp.asarray(rng.normal(size=(40,)).astype(np.float32)),
        }
        results = [None] * world
        errors = []

        def run(rank):
            client = MagicMock()
            client.quorum.return_value = quorum_result(
                store_address=store.address(),
                max_rank=rank, max_world_size=world,
                replica_rank=rank, replica_world_size=world)
            client.should_commit.return_value = True
            m = make_manager(
                client, comm=HostCommunicator(timeout_sec=30),
                allreduce_bucket_bytes=64,  # force multi-bucket
                allreduce_wire_dtype=jnp.bfloat16)
            try:
                m.step()
                scaled = jax.tree_util.tree_map(
                    lambda a: a * (rank + 1), tree)
                results[rank] = m.allreduce(scaled).result(timeout=30)
                assert m.should_commit()
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                m.shutdown()

        threads = [_t.Thread(target=run, args=(r,)) for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        alive = [t for t in threads if t.is_alive()]
        store.shutdown()
        assert not alive, "packed allreduce deadlocked on empty leaf"
        assert not errors, errors
        # One bf16 quantization of each local contribution bounds the
        # error of the mean: |got - exact| <= (|q(x1)-x1| + |q(x2)-x2|)/2
        # (evaluated in f64, with an ulp cushion for the f32 fold).
        x64 = host_leaf.astype(np.float64)
        q1 = host_leaf.astype(jnp.bfloat16).astype(np.float64)
        q2 = (host_leaf * 2).astype(jnp.bfloat16).astype(np.float64)
        bound = (np.abs(q1 - x64) + np.abs(q2 - 2 * x64)) / 2
        cushion = 1e-6 * (1.0 + np.abs(1.5 * x64))
        for out in results:
            assert out["empty"].shape == (0, 5)
            got = np.asarray(out["host"]).astype(np.float64)
            assert np.all(np.abs(got - 1.5 * x64) <= bound + cushion)
        # Cross-rank bitwise agreement (canonical-order f32 fold).
        np.testing.assert_array_equal(np.asarray(results[0]["host"]),
                                      np.asarray(results[1]["host"]))
        np.testing.assert_array_equal(np.asarray(results[0]["dev"]),
                                      np.asarray(results[1]["dev"]))

    @requires_native
    def test_bf16_wire_compression_close_to_exact(self):
        """allreduce_wire_dtype=bfloat16 quantizes each local contribution
        once; the sum/scale stay f32, so the result tracks the exact mean
        within bf16 rounding (~3 decimal digits)."""
        import threading as _t

        import jax.numpy as jnp

        from torchft_tpu._native import Store
        from torchft_tpu.backends.host import HostCommunicator

        store = Store(bind="127.0.0.1:0")
        world = 2
        rng = np.random.default_rng(1)
        base = rng.normal(size=(257,)).astype(np.float32)
        results = [None] * world
        errors = []

        def run(rank):
            client = MagicMock()
            client.quorum.return_value = quorum_result(
                store_address=store.address(),
                max_rank=rank, max_world_size=world,
                replica_rank=rank, replica_world_size=world)
            client.should_commit.return_value = True
            m = Manager(
                comm=HostCommunicator(timeout_sec=30),
                load_state_dict=MagicMock(),
                state_dict=lambda: {},
                min_replica_size=2, rank=0, world_size=1,
                replica_id=f"wire{rank}",
                allreduce_wire_dtype=jnp.bfloat16,
                _manager_client=client,
            )
            try:
                m.step()
                tree = {"g": jnp.asarray(base * (rank + 1))}
                results[rank] = m.allreduce(tree).result(timeout=30)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                m.shutdown()

        threads = [_t.Thread(target=run, args=(r,)) for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        alive = [t for t in threads if t.is_alive()]
        store.shutdown()
        assert not alive, "wire-compressed allreduce deadlocked"
        assert not errors, errors
        for out in results:
            assert out is not None, "worker produced no result"
            # Callers must get their original dtype back, not the wire one.
            assert np.dtype(out["g"].dtype) == np.float32
            got = np.asarray(out["g"])
            np.testing.assert_allclose(got, base * 1.5, rtol=1e-2, atol=1e-2)

    @requires_native
    def test_wire_ring_matches_upcast_before_ring(self):
        """The wire-dtype ring must match the upcast-before-ring path it
        replaced within one bf16 quantization of each local contribution.
        At world 2 the match is exact: raw bf16 contributions cross the
        wire once and fold into an f32 accumulator — the same values,
        sum, and 1/n the old path computed after upcasting on the host —
        so the results are bitwise identical."""
        import threading as _t

        import jax.numpy as jnp

        from torchft_tpu._native import Store
        from torchft_tpu.backends.host import HostCommunicator

        store = Store(bind="127.0.0.1:0")
        world = 2
        rng = np.random.default_rng(3)
        base = rng.normal(size=(513,)).astype(np.float32)
        results = [None] * world
        errors = []

        def run(rank):
            client = MagicMock()
            client.quorum.return_value = quorum_result(
                store_address=store.address(),
                max_rank=rank, max_world_size=world,
                replica_rank=rank, replica_world_size=world)
            client.should_commit.return_value = True
            m = make_manager(
                client, comm=HostCommunicator(timeout_sec=30),
                allreduce_wire_dtype=jnp.bfloat16)
            try:
                m.step()
                tree = {"g": jnp.asarray(base * (rank + 1))}
                results[rank] = m.allreduce(tree).result(timeout=30)
                assert m.should_commit()
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                m.shutdown()

        threads = [_t.Thread(target=run, args=(r,)) for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        alive = [t for t in threads if t.is_alive()]
        store.shutdown()
        assert not alive, "wire ring deadlocked"
        assert not errors, errors
        # Upcast-before-ring expectation: quantize each contribution
        # once (the device pack's bf16 cast), sum + 1/n in f32.
        q = [np.asarray(jnp.asarray(base * (r + 1))
                        .astype(jnp.bfloat16).astype(jnp.float32))
             for r in range(world)]
        expected = (q[0] + q[1]) / 2
        x64 = base.astype(np.float64)
        exact = 1.5 * x64
        bound = (np.abs(q[0].astype(np.float64) - x64)
                 + np.abs(q[1].astype(np.float64) - 2 * x64)) / 2
        cushion = 1e-6 * (1.0 + np.abs(exact))
        for out in results:
            got = np.asarray(out["g"])
            assert np.dtype(got.dtype) == np.float32
            np.testing.assert_array_equal(got, expected)
            diff = np.abs(got.astype(np.float64) - exact)
            assert np.all(diff <= bound + cushion)
        np.testing.assert_array_equal(
            np.asarray(results[0]["g"]), np.asarray(results[1]["g"]))

    def test_state_dict_roundtrip(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = True
        m = make_manager(client)
        try:
            m.step()
            m.should_commit()
            sd = m.state_dict()
            assert sd == {"step": 1, "batches_committed": 0}
            m.load_state_dict({"step": 42, "batches_committed": 84})
            assert m.current_step() == 42
            assert m.batches_committed() == 84
        finally:
            m.shutdown()


class TestSchedule:
    """The memoized bucket/chunk schedule: metadata-only derivation (so
    participant, healer, and spare ranks agree byte-for-byte) and
    steady-state caching (so later steps skip the Python re-derivation)."""

    METAS = (
        ((17, 3), "float32"),
        ((130,), "float32"),
        ((0, 5), "float32"),
        ((5,), "float64"),
        ((6,), "int64"),
    )

    def test_cross_rank_fingerprint_identical(self):
        import jax.numpy as jnp

        a = _derive_schedule(self.METAS, 256, jnp.bfloat16)
        b = _derive_schedule(self.METAS, 256, jnp.bfloat16)
        assert a.fingerprint == b.fingerprint
        assert a.buckets == b.buckets
        for cs_a, cs_b in zip(a.chunks, b.chunks):
            for ca, cb in zip(cs_a, cs_b):
                assert (ca.orig, ca.wire, ca.idx, ca.sizes, ca.shapes,
                        ca.total) == (cb.orig, cb.wire, cb.idx, cb.sizes,
                                      cb.shapes, cb.total)
        # Geometry invariants: every leaf appears exactly once; 0-size
        # leaves contribute 0 elements; chunk totals match their sizes.
        seen = sorted(i for cs in a.chunks for c in cs for i in c.idx)
        assert seen == list(range(len(self.METAS)))
        for cs in a.chunks:
            for c in cs:
                assert c.total == sum(c.sizes)
        flat_sizes = {i: s for cs in a.chunks for c in cs
                      for i, s in zip(c.idx, c.sizes)}
        assert flat_sizes[2] == 0  # the (0, 5) leaf

    def test_wire_fields_change_fingerprint(self):
        import jax.numpy as jnp

        exact = _derive_schedule(self.METAS, 256, None)
        wire = _derive_schedule(self.METAS, 256, jnp.bfloat16)
        assert exact.fingerprint != wire.fingerprint
        # Wire compression narrows float chunks but never int chunks.
        wire_dtypes = {str(c.wire) for cs in wire.chunks for c in cs}
        assert "bfloat16" in wire_dtypes
        assert any(str(c.wire) == "int64" for cs in wire.chunks
                   for c in cs)

    def test_schedule_cached_across_participant_and_healer_views(self):
        """Participant (device leaves), healer, and spare (host zero
        leaves) ranks must land on ONE cached schedule: the cache key is
        metadata-only, so the same object — hence byte-identical chunk
        geometry — serves all three roles."""
        import jax.numpy as jnp

        from torchft_tpu.manager import _zero_like

        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = True
        m = make_manager(client, allreduce_bucket_bytes=64,
                         allreduce_wire_dtype=jnp.bfloat16)
        try:
            tree = {"a": jnp.ones((9, 3), jnp.float32),
                    "b": jnp.zeros((40,), jnp.float32)}
            leaves, treedef = jax.tree_util.tree_flatten(tree)
            healer_leaves = [_zero_like(x) for x in leaves]
            s_part = m._get_schedule(treedef, leaves)
            s_heal = m._get_schedule(treedef, healer_leaves)
            assert s_part is s_heal  # one cache entry, identical geometry
            assert m._get_schedule(treedef, leaves) is s_part  # steady state
        finally:
            m.shutdown()


def _make_test_rings(world):
    """Socketpair ring for world thread-ranks: pair[i] connects rank i's
    next-hop to rank (i+1)%world's prev-hop. No store rendezvous, no
    native control plane — the real ring transport over real sockets."""
    import socket as _socket

    from torchft_tpu.backends.host import _Ring

    pairs = [_socket.socketpair() for _ in range(world)]
    return [_Ring(pairs[r][0], pairs[(r - 1) % world][1], _socket.socket())
            for r in range(world)]


def _wired_comm(ring, rank, world):
    """HostCommunicator with the store rendezvous replaced by a
    pre-wired ring, so the full pipelined allreduce — pack, async D2H,
    wire ring, device unpack — runs without the native library."""
    from torchft_tpu.backends.host import HostCommunicator

    class WiredComm(HostCommunicator):
        def configure(self, store_addr, rank, world_size):
            pass  # pre-wired

    c = WiredComm(timeout_sec=15)
    c._ring, c._rank, c._world = ring, rank, world
    return c


class TestWireRingPipelined:
    """End-to-end pipelined allreduce over real ring sockets (socketpair
    transport, mocked control plane): the tier-1 spelling of the
    numerics guarantees that don't need the native store."""

    def _run(self, world, tree_fn, **mkw):
        import threading as _t

        rings = _make_test_rings(world)
        results = [None] * world
        metrics = [None] * world
        errors = []

        def run(rank):
            client = MagicMock()
            client.quorum.return_value = quorum_result(
                max_rank=rank, max_world_size=world,
                replica_rank=rank, replica_world_size=world)
            client.should_commit.return_value = True
            m = make_manager(client,
                             comm=_wired_comm(rings[rank], rank, world),
                             min_replica_size=world, **mkw)
            try:
                m.step()
                results[rank] = m.allreduce(tree_fn(rank)).result(
                    timeout=30)
                err = m.errored()
                assert err is None, err
                assert m.should_commit()
                metrics[rank] = m.metrics()
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                m.shutdown()

        threads = [_t.Thread(target=run, args=(r,)) for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        alive = [t for t in threads if t.is_alive()]
        for r in rings:
            r.close()
        assert not alive, "pipelined allreduce deadlocked"
        assert not errors, errors
        return results, metrics

    BASE = {
        "a": np.random.default_rng(0).normal(size=(257, 3)).astype(
            np.float32),
        "b": np.random.default_rng(1).normal(size=(1000,)).astype(
            np.float32),
        "empty": np.zeros((0, 5), np.float32),
        "i": np.arange(6, dtype=np.int32),
    }

    def test_exact_mode_bitwise(self):
        import jax.numpy as jnp

        def tf(rank):
            return jax.tree_util.tree_map(
                lambda a: jnp.asarray(a) * (rank + 1), self.BASE)

        results, metrics = self._run(2, tf, allreduce_bucket_bytes=1024)
        for out in results:
            for k in ("a", "b"):
                np.testing.assert_array_equal(
                    np.asarray(out[k]),
                    (self.BASE[k] * 1.5).astype(np.float32))
            assert out["empty"].shape == (0, 5)
            np.testing.assert_array_equal(np.asarray(out["i"]),
                                          (self.BASE["i"] * 3) // 2)
        mx = metrics[0]
        # Fetch split populated; exact mode moves identical bytes on
        # both legs (D2H and ring) at world 2.
        assert mx["allreduce_fetch_dispatch_ms_total"] > 0
        assert mx["allreduce_fetch_wait_ms_total"] > 0
        assert mx["allreduce_ring_wire_bytes_total"] == \
            mx["allreduce_wire_bytes_total"] > 0

    def test_bf16_wire_matches_upcast_path_bitwise_at_world2(self):
        import jax.numpy as jnp

        def tf(rank):
            return jax.tree_util.tree_map(
                lambda a: jnp.asarray(a) * (rank + 1), self.BASE)

        results, metrics = self._run(
            2, tf, allreduce_bucket_bytes=1024,
            allreduce_wire_dtype=jnp.bfloat16)
        for k in ("a", "b"):
            q = [np.asarray(
                jnp.ravel(jnp.asarray(self.BASE[k] * (r + 1)))
                .astype(jnp.bfloat16).astype(jnp.float32))
                .reshape(self.BASE[k].shape) for r in range(2)]
            expected = (q[0] + q[1]) / 2
            # Quantization bound evaluated in f64 (f32 evaluation of the
            # bound itself would flake on ulps — diff and bound are
            # mathematically EQUAL here), with an ulp cushion for the
            # f32 rounding of the accumulator sum.
            x64 = self.BASE[k].astype(np.float64)
            exact = 1.5 * x64
            bound = (np.abs(q[0].astype(np.float64) - x64)
                     + np.abs(q[1].astype(np.float64) - 2 * x64)) / 2
            cushion = 1e-6 * (1.0 + np.abs(exact))
            for out in results:
                got = np.asarray(out[k])
                assert np.dtype(got.dtype) == np.float32
                # Bitwise the upcast-before-ring result, and within one
                # bf16 quantization per contribution of the exact mean.
                np.testing.assert_array_equal(got, expected)
                diff = np.abs(got.astype(np.float64) - exact)
                assert np.all(diff <= bound + cushion)
        mx = metrics[0]
        # Float payload halves on BOTH legs; the int chunk stays wide.
        float_bytes = sum(self.BASE[k].size * 4 for k in ("a", "b"))
        int_bytes = self.BASE["i"].size * 4
        assert mx["allreduce_wire_bytes_total"] == \
            float_bytes / 2 + int_bytes
        assert mx["allreduce_ring_wire_bytes_total"] == \
            float_bytes / 2 + int_bytes

    def test_world3_wire_cross_rank_bitwise(self):
        import jax.numpy as jnp

        def tf(rank):
            return {"g": jnp.asarray(self.BASE["b"] * (rank + 1))}

        results, _ = self._run(3, tf, allreduce_wire_dtype=jnp.bfloat16)
        # Canonical-rank-order fold: all three ranks bitwise identical.
        g0 = np.asarray(results[0]["g"])
        np.testing.assert_array_equal(g0, np.asarray(results[1]["g"]))
        np.testing.assert_array_equal(g0, np.asarray(results[2]["g"]))
        q = [np.asarray(jnp.asarray(self.BASE["b"] * (r + 1))
                        .astype(jnp.bfloat16).astype(jnp.float32))
             for r in range(3)]
        np.testing.assert_array_equal(g0, ((q[0] + q[1]) + q[2]) / 3)

    def test_healer_gets_averaged_grads_without_contributing(self):
        import jax.numpy as jnp

        def tf(rank):
            return {"g": jnp.asarray(self.BASE["b"] * (rank + 1))}

        # Rank 1 is a healer (max_rank None): zero contribution, but it
        # still receives the participants' average.
        import threading as _t

        rings = _make_test_rings(2)
        results = [None] * 2
        errors = []

        def run(rank):
            client = MagicMock()
            client.quorum.return_value = quorum_result(
                max_rank=(0 if rank == 0 else None), max_world_size=1,
                replica_rank=rank, replica_world_size=2,
                heal=(rank == 1))
            client.should_commit.return_value = True
            m = make_manager(client,
                             comm=_wired_comm(rings[rank], rank, 2),
                             min_replica_size=1)
            try:
                m.step()
                results[rank] = m.allreduce(tf(rank)).result(
                    timeout=30)
                err = m.errored()
                assert err is None, err
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                m.shutdown()

        state = {"user": {}, "torchft": {"step": 1,
                                         "batches_committed": 0}}
        # Patch ONCE on the main thread around both workers: mock.patch
        # mutates the class attribute, so nested per-thread patching
        # races on unpatch and can leave the mock installed globally.
        cp = patch(
            "torchft_tpu.manager.CheckpointServer.load_from_address",
            return_value=state)
        pc = patch("torchft_tpu.manager.ManagerClient")
        with cp, pc:
            threads = [_t.Thread(target=run, args=(r,))
                       for r in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
        for r in rings:
            r.close()
        assert not errors, errors
        # Participant world is 1; healer contributed zeros. Both see the
        # participant's grads unscaled (sum/1).
        np.testing.assert_array_equal(np.asarray(results[0]["g"]),
                                      self.BASE["b"])
        np.testing.assert_array_equal(np.asarray(results[1]["g"]),
                                      self.BASE["b"])
