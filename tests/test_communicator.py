"""Communicator layer tests.

Mirrors the reference's process-group test strategy
(/root/reference/torchft/process_group_test.py): dummy-backend counters,
error-latching wrapper semantics, and real collectives with all ranks as
threads in one process over localhost.
"""

import threading
from concurrent.futures import Future

import numpy as np
import pytest

from torchft_tpu._native import Store
from torchft_tpu.backends.host import HostCommunicator
from torchft_tpu.communicator import (
    Communicator,
    CommunicatorError,
    DummyCommunicator,
    ErrorSwallowingCommunicator,
)


class TestDummy:
    def test_counters_and_identity(self):
        d = DummyCommunicator(rank=0, world_size=3)
        d.configure("ignored/prefix", 1, 3)
        tree = {"g": np.ones(4)}
        out = d.allreduce(tree).result()
        assert out is tree
        assert d.allgather(tree).result() == [tree, tree, tree]
        assert d.configure_count == 1
        assert d.allreduce_count == 1
        assert d.allgather_count == 1
        assert d.size() == 3 and d.rank() == 1

    def test_allreduce_wire_default_upcasts(self):
        """The ABC's default allreduce_wire upcasts wire buffers to the
        accumulator dtype locally and rides allreduce — backends without
        a wire-aware transport keep working (compression then only thins
        the D2H leg, the pre-wire-ring behavior)."""
        import jax.numpy as jnp

        d = DummyCommunicator(rank=0, world_size=2)
        wire = np.arange(4, dtype=np.float32).astype(jnp.bfloat16)
        exact = np.arange(3, dtype=np.float32)
        out = d.allreduce_wire([wire, exact],
                               ["float32", "float32"]).result()
        assert d.allreduce_count == 1
        assert out[0].dtype == np.float32
        np.testing.assert_array_equal(out[0],
                                      np.arange(4, dtype=np.float32))
        # Already-accumulator-dtype buffers pass through without a copy
        # (ravel/astype may re-wrap, but never duplicate the data).
        assert np.shares_memory(out[1], exact)

    def test_ring_bytes_default_zero(self):
        assert DummyCommunicator().ring_bytes_total() == 0.0


class _FailingComm(Communicator):
    """Raises on every collective (sync or async depending on mode)."""

    def __init__(self, sync_raise: bool):
        self.sync_raise = sync_raise

    def configure(self, store_addr, rank, world_size):
        pass

    def _fail(self):
        if self.sync_raise:
            raise CommunicatorError("boom")
        f: Future = Future()
        f.set_exception(CommunicatorError("boom"))
        return f

    def allreduce(self, tree, op="sum"):
        return self._fail()

    def broadcast(self, tree, root=0):
        return self._fail()

    def allgather(self, tree):
        return self._fail()

    def size(self):
        return 2

    def rank(self):
        return 0


class TestErrorSwallowing:
    @pytest.mark.parametrize("sync_raise", [True, False])
    def test_latches_and_swallows(self, sync_raise):
        errors = []
        comm = ErrorSwallowingCommunicator(
            _FailingComm(sync_raise), on_error=errors.append)
        tree = {"g": np.full(3, 7.0)}
        out = comm.allreduce(tree).result(timeout=5)
        # Error swallowed: input returned unchanged, error latched.
        assert out is tree
        assert isinstance(comm.error(), CommunicatorError)
        assert len(errors) == 1
        # Subsequent ops short-circuit without touching the backend.
        out2 = comm.allreduce(tree).result(timeout=5)
        assert out2 is tree
        assert len(errors) == 1  # only first error reported
        # Reconfigure clears the latch.
        comm.configure("addr/p", 0, 2)
        assert comm.error() is None

    @pytest.mark.parametrize("sync_raise", [True, False])
    def test_allreduce_wire_swallows_to_upcast_fallback(self, sync_raise):
        """allreduce_wire failures swallow like allreduce's: the caller
        gets the locally-upcast contributions back (structure preserved,
        values = this rank's own), and the error latches."""
        comm = ErrorSwallowingCommunicator(_FailingComm(sync_raise))
        wire = np.arange(5, dtype=np.float32)
        out = comm.allreduce_wire([wire], ["float32"]).result(timeout=5)
        assert isinstance(comm.error(), CommunicatorError)
        np.testing.assert_array_equal(out[0], wire)

    def test_wire_contract_forwarded_inward(self):
        """Wrappers must forward allreduce_wire / ring_bytes_total to the
        wrapped backend — a wrapper falling back to the ABC default would
        silently upcast before the ring and double the wire bytes."""
        calls = {}

        class Inner(DummyCommunicator):
            def allreduce_wire(self, buffers, orig_dtypes, op="sum"):
                calls["wire"] = (len(list(buffers)), list(orig_dtypes))
                return super().allreduce_wire(buffers, orig_dtypes, op)

            def ring_bytes_total(self):
                return 123.0

        comm = ErrorSwallowingCommunicator(Inner())
        comm.allreduce_wire([np.ones(2, np.float32)],
                            ["float32"]).result(timeout=5)
        assert calls["wire"] == (1, ["float32"])
        assert comm.ring_bytes_total() == 123.0


def _run_ranks(world_size, fn):
    """Run fn(rank) in world_size threads; propagate the first exception."""
    results = [None] * world_size
    errors = []

    def wrap(r):
        try:
            results[r] = fn(r)
        except Exception as e:  # noqa: BLE001
            errors.append((r, e))

    threads = [threading.Thread(target=wrap, args=(r,))
               for r in range(world_size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    if errors:
        raise errors[0][1]
    return results


@pytest.fixture
def store():
    import conftest

    if not conftest.native_available():
        pytest.skip("native control-plane library unavailable "
                    "(no C++ toolchain)")
    s = Store(bind="127.0.0.1:0")
    yield s
    s.shutdown()


class TestHostCommunicator:
    @pytest.mark.parametrize("world_size", [2, 3, 4])
    def test_allreduce_sum(self, store, world_size):
        addr = store.address()
        comms = [HostCommunicator(timeout_sec=30) for _ in range(world_size)]

        def run(rank):
            comm = comms[rank]
            comm.configure(f"{addr}/q1", rank, world_size)
            tree = {
                "a": np.full((5, 3), float(rank + 1), dtype=np.float32),
                "b": np.arange(7, dtype=np.float64) * (rank + 1),
                "c": np.full(2, rank, dtype=np.int32),
            }
            return comm.allreduce(tree).result(timeout=30)

        results = _run_ranks(world_size, run)
        tot = sum(range(1, world_size + 1))
        for out in results:
            np.testing.assert_allclose(
                out["a"], np.full((5, 3), float(tot), dtype=np.float32))
            np.testing.assert_allclose(
                out["b"], np.arange(7, dtype=np.float64) * tot)
            np.testing.assert_array_equal(
                out["c"],
                np.full(2, sum(range(world_size)), dtype=np.int32))
            assert out["a"].dtype == np.float32
            assert out["c"].dtype == np.int32
        for c in comms:
            c.shutdown()

    def test_allreduce_config_skew_fails_fast(self, store):
        # Mismatched (bucket_bytes, wire_dtype) across groups would wedge
        # every bucketed ring collective on mismatched collective counts;
        # the fingerprint check (set by Manager, verified during the store
        # rendezvous) must surface it as a clear error instead.
        addr = store.address()
        comms = [HostCommunicator(timeout_sec=5) for _ in range(2)]
        comms[0].allreduce_config_fingerprint = "bucket_bytes=4194304;bf16"
        comms[1].allreduce_config_fingerprint = "bucket_bytes=1048576;None"

        def run(rank):
            comms[rank].configure(f"{addr}/qskew", rank, 2)

        with pytest.raises(RuntimeError, match="allreduce config skew"):
            _run_ranks(2, run)
        for c in comms:
            c.shutdown()

    def test_matching_config_fingerprint_passes(self, store):
        addr = store.address()
        comms = [HostCommunicator(timeout_sec=30) for _ in range(2)]
        for c in comms:
            c.allreduce_config_fingerprint = "bucket_bytes=4194304;None"

        def run(rank):
            comms[rank].configure(f"{addr}/qok", rank, 2)
            return comms[rank].allreduce(
                {"a": np.ones(4, np.float32)}).result(timeout=30)

        for out in _run_ranks(2, run):
            np.testing.assert_allclose(out["a"], np.full(4, 2.0))
        for c in comms:
            c.shutdown()

    def test_allreduce_mean(self, store):
        addr = store.address()
        comms = [HostCommunicator(timeout_sec=30) for _ in range(2)]

        def run(rank):
            comm = comms[rank]
            comm.configure(f"{addr}/qm", rank, 2)
            return comm.allreduce(
                {"g": np.full(4, float(rank), dtype=np.float32)},
                op="mean").result(timeout=30)

        for out in _run_ranks(2, run):
            np.testing.assert_allclose(out["g"], np.full(4, 0.5))
        for c in comms:
            c.shutdown()

    def test_broadcast(self, store):
        addr = store.address()
        world = 3
        comms = [HostCommunicator(timeout_sec=30) for _ in range(world)]

        def run(rank):
            comm = comms[rank]
            comm.configure(f"{addr}/qb", rank, world)
            tree = {"w": np.full(6, float(rank), dtype=np.float32)}
            return comm.broadcast(tree, root=1).result(timeout=30)

        for out in _run_ranks(world, run):
            np.testing.assert_allclose(out["w"], np.full(6, 1.0))
        for c in comms:
            c.shutdown()

    def test_allgather(self, store):
        addr = store.address()
        world = 3
        comms = [HostCommunicator(timeout_sec=30) for _ in range(world)]

        def run(rank):
            comm = comms[rank]
            comm.configure(f"{addr}/qg", rank, world)
            return comm.allgather(
                {"v": np.full(3, float(rank))}).result(timeout=30)

        for out in _run_ranks(world, run):
            assert len(out) == world
            for r in range(world):
                np.testing.assert_allclose(out[r]["v"], np.full(3, float(r)))
        for c in comms:
            c.shutdown()

    def test_world_size_one_is_noop(self):
        comm = HostCommunicator()
        comm.configure("unused/prefix", 0, 1)
        tree = {"x": np.ones(3)}
        assert comm.allreduce(tree).result(timeout=5) is tree
        comm.shutdown()

    def test_reconfigure_shrink(self, store):
        """3-rank ring reconfigures to a 2-rank ring (a group died)."""
        addr = store.address()
        comms = [HostCommunicator(timeout_sec=30) for _ in range(3)]

        def run3(rank):
            comms[rank].configure(f"{addr}/e1", rank, 3)
            return comms[rank].allreduce(
                {"g": np.ones(4, dtype=np.float32)}).result(timeout=30)

        for out in _run_ranks(3, run3):
            np.testing.assert_allclose(out["g"], np.full(4, 3.0))

        # rank 2 "dies"; ranks 0,1 reconfigure onto a new prefix.
        def run2(rank):
            comms[rank].configure(f"{addr}/e2", rank, 2)
            return comms[rank].allreduce(
                {"g": np.ones(4, dtype=np.float32)}).result(timeout=30)

        for out in _run_ranks(2, run2):
            np.testing.assert_allclose(out["g"], np.full(4, 2.0))
        for c in comms:
            c.shutdown()

    def test_peer_death_aborts_with_error(self, store):
        """If a peer dies mid-collective, survivors get CommunicatorError,
        not a hang (the reference needed subprocess isolation for this;
        socket closure gives it to us directly)."""
        addr = store.address()
        comms = [HostCommunicator(timeout_sec=30) for _ in range(2)]

        def run(rank):
            comms[rank].configure(f"{addr}/dead", rank, 2)
            if rank == 1:
                comms[1].shutdown()  # dies before the collective
                return None
            return comms[0].allreduce({"g": np.ones(1024)})

        results = _run_ranks(2, run)
        with pytest.raises(CommunicatorError):
            results[0].result(timeout=30)
        comms[0].shutdown()


class _StubManager:
    """Just enough Manager surface for ManagedCommunicator (the real
    contract: errors feed report_error, size() is num_participants —
    reference ManagedProcessGroup, process_group.py:443-468)."""

    def __init__(self, comm, participants=3):
        self._comm = comm
        self._participants = participants
        self._error = None

    def report_error(self, e):
        self._error = e

    def errored(self):
        return self._error

    def num_participants(self):
        return self._participants


class TestManagedCommunicator:
    def make(self, sync_raise=None, participants=3):
        from torchft_tpu.communicator import ManagedCommunicator

        comm = (DummyCommunicator(rank=1, world_size=5)
                if sync_raise is None else _FailingComm(sync_raise))
        mgr = _StubManager(comm, participants)
        return ManagedCommunicator(mgr), mgr, comm

    def test_size_is_num_participants_not_world(self):
        mc, mgr, comm = self.make(participants=2)
        # the underlying world is 5, but 1/n normalization must track the
        # quorum's participant count
        assert comm.size() == 5
        assert mc.size() == 2
        mgr._participants = 4
        assert mc.size() == 4
        assert mc.rank() == 1

    def test_happy_path_delegates(self):
        mc, mgr, comm = self.make()
        tree = {"g": np.ones(3)}
        assert mc.allreduce(tree).result() is tree
        assert mc.broadcast(tree).result() is tree
        assert mc.allgather(tree).result() == [tree] * 5
        assert mgr.errored() is None
        assert comm.allreduce_count == 1

    @pytest.mark.parametrize("sync_raise", [True, False])
    def test_error_reported_to_manager_vote(self, sync_raise):
        mc, mgr, _ = self.make(sync_raise=sync_raise)
        tree = {"g": np.ones(3)}
        out = mc.allreduce(tree).result(timeout=5)
        # error never propagates to the caller: the input is returned so
        # every rank keeps an identical step structure...
        assert out is tree
        # ...and the failure reaches the manager, which will vote False
        assert isinstance(mgr.errored(), CommunicatorError)

    def test_skips_collectives_once_errored(self):
        mc, mgr, comm = self.make()
        mgr.report_error(CommunicatorError("prior failure"))
        tree = {"g": np.ones(3)}
        assert mc.allreduce(tree).result() is tree
        assert comm.allreduce_count == 0  # underlying comm never touched
        assert mc.allgather(tree).result() == [tree] * mc.size()


class TestMeshCommunicator:
    """On-device full-membership fast path + host fallback
    (backends/mesh.py)."""

    def make_world(self, n, timeout=10):
        from torchft_tpu.backends.mesh import MeshCommunicator, MeshWorld

        world = MeshWorld(num_groups=n, timeout_sec=timeout)
        return world, [MeshCommunicator(world, group_index=i,
                                        timeout_sec=timeout)
                       for i in range(n)]

    def test_full_membership_allreduce_on_device(self):
        import jax
        import jax.numpy as jnp

        world, comms = self.make_world(3)

        def run(rank):
            comms[rank].configure("store/q1", rank, 3)
            assert comms[rank].mode() == "mesh"
            assert comms[rank].wants_device_arrays
            tree = {"g": jnp.full((4,), float(rank + 1)),
                    "h": np.full((2, 2), rank, np.float32)}
            return comms[rank].allreduce(tree).result(timeout=30)

        for rank, out in enumerate(_run_ranks(3, run)):
            np.testing.assert_allclose(np.asarray(out["g"]), np.full(4, 6.0))
            np.testing.assert_allclose(np.asarray(out["h"]),
                                       np.full((2, 2), 3.0))
            # device-array inputs come back as device arrays
            assert isinstance(out["g"], jax.Array)

    def test_mean(self):
        import jax.numpy as jnp

        world, comms = self.make_world(2)

        def run(rank):
            comms[rank].configure("store/qm", rank, 2)
            return comms[rank].allreduce(
                {"g": jnp.full((3,), float(rank * 2))},
                op="mean").result(timeout=30)

        for out in _run_ranks(2, run):
            np.testing.assert_allclose(np.asarray(out["g"]), np.full(3, 1.0))

    def test_mean_bfloat16(self):
        """bfloat16 is not np.inexact — the mean path must still divide,
        not floor-divide sub-1.0 gradients to zero."""
        import jax.numpy as jnp

        world, comms = self.make_world(2)

        def run(rank):
            comms[rank].configure("store/qbf", rank, 2)
            return comms[rank].allreduce(
                {"g": jnp.full((4,), 0.25, jnp.bfloat16)},
                op="mean").result(timeout=30)

        for out in _run_ranks(2, run):
            assert out["g"].dtype == jnp.bfloat16
            np.testing.assert_allclose(
                np.asarray(out["g"], np.float32), np.full(4, 0.25))

    def test_wrappers_forward_wants_device_arrays(self):
        from torchft_tpu.backends.mesh import MeshCommunicator, MeshWorld
        from torchft_tpu.communicator import ErrorSwallowingCommunicator

        mesh = MeshCommunicator(MeshWorld(num_groups=1))
        mesh.configure("store/qw", 0, 1)
        assert mesh.wants_device_arrays
        assert ErrorSwallowingCommunicator(mesh).wants_device_arrays
        assert not ErrorSwallowingCommunicator(
            DummyCommunicator()).wants_device_arrays

    def test_broadcast_and_allgather(self):
        import jax.numpy as jnp

        world, comms = self.make_world(2)

        def run(rank):
            comms[rank].configure("store/qb", rank, 2)
            bc = comms[rank].broadcast(
                {"w": jnp.full((2,), float(rank + 5))}, root=1
            ).result(timeout=30)
            ag = comms[rank].allgather({"r": np.int64(rank)}).result(
                timeout=30)
            return bc, ag

        for rank, (bc, ag) in enumerate(_run_ranks(2, run)):
            np.testing.assert_allclose(np.asarray(bc["w"]), np.full(2, 6.0))
            assert [int(t["r"]) for t in ag] == [0, 1]

    def test_sharded_leaves_keep_their_sharding(self):
        """Each group's gradient lives on its own sub-mesh; the reduced
        result must come back on that same sharding (on real multi-slice
        hardware XLA owns the transfers — here we assert placement)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        devs = jax.devices()
        assert len(devs) >= 8
        group_meshes = [Mesh(np.array(devs[:4]), ("dp",)),
                        Mesh(np.array(devs[4:8]), ("dp",))]
        world, comms = self.make_world(2)

        def run(rank):
            comms[rank].configure("store/qs", rank, 2)
            sh = NamedSharding(group_meshes[rank], P("dp"))
            g = jax.device_put(jnp.full((8, 4), float(rank + 1)), sh)
            out = comms[rank].allreduce({"g": g}).result(timeout=30)
            return out, sh

        for rank, (out, sh) in enumerate(_run_ranks(2, run)):
            np.testing.assert_allclose(np.asarray(out["g"]),
                                       np.full((8, 4), 3.0))
            assert out["g"].sharding == sh

    def test_partial_membership_uses_host_fallback(self, store):
        from torchft_tpu.backends.mesh import MeshCommunicator, MeshWorld

        world = MeshWorld(num_groups=3, timeout_sec=10)
        comms = [MeshCommunicator(world, group_index=i) for i in range(2)]
        addr = store.address()

        def run(rank):
            # 2 of 3 static groups alive: must leave the device
            comms[rank].configure(f"{addr}/fb", rank, 2)
            assert comms[rank].mode() == "host"
            assert not comms[rank].wants_device_arrays
            return comms[rank].allreduce(
                {"g": np.full(4, float(rank + 1), np.float32)}
            ).result(timeout=30)

        for out in _run_ranks(2, run):
            np.testing.assert_allclose(out["g"], np.full(4, 3.0))
        for c in comms:
            c.shutdown()

    def test_peer_never_arrives_times_out(self):
        world, comms = self.make_world(2, timeout=0.5)
        comms[0].configure("store/qt", 0, 2)
        fut = comms[0].allreduce({"g": np.ones(2)})
        with pytest.raises(CommunicatorError, match="timed out"):
            fut.result(timeout=10)

    def test_wedged_device_op_watchdog_demotes_to_host(self, monkeypatch,
                                                       store):
        """VERDICT r2 #4: the device-side reduction gets a deadline (the
        rendezvous timer only bounds waiting for peers). An injected hang
        must (1) fail every waiter's future within the deadline so the
        error latches into the commit vote, and (2) poison the world so
        the next configure demotes to the host ring instead of feeding
        more work to a wedged runtime."""
        import threading as _threading
        import time

        from torchft_tpu.backends import mesh as mesh_mod
        from torchft_tpu.backends.mesh import MeshCommunicator, MeshWorld

        hang = _threading.Event()
        monkeypatch.setattr(mesh_mod, "_jit_tree_sum",
                            lambda *trees: hang.wait(60))
        world = MeshWorld(num_groups=2, timeout_sec=30)
        world.device_op_timeout_sec = 0.5
        comms = [MeshCommunicator(world, group_index=i) for i in range(2)]
        for i, c in enumerate(comms):
            c.configure("store/q1", i, 2)
        assert all(c.mode() == "mesh" for c in comms)

        futs = {}
        def contribute(i):
            futs[i] = comms[i].allreduce({"g": np.ones(4, np.float32)})
        ts = [_threading.Thread(target=contribute, args=(i,))
              for i in range(2)]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        for i in range(2):
            with pytest.raises(CommunicatorError, match="deadline"):
                futs[i].result(timeout=10)
        assert time.perf_counter() - t0 < 10  # deadline, not rendezvous timer
        assert world.poisoned() is not None

        # Next quorum: full membership would normally restore mesh mode,
        # but the poisoned world must demote to the elastic host ring —
        # which still works end to end.
        prefix = store.address() + "/q2"
        outs = {}
        def reconfigure_and_reduce(i):
            comms[i].configure(prefix, i, 2)
            outs[i] = comms[i].allreduce(
                {"g": np.full(4, float(i + 1), np.float32)}).result(30)
        ts = [_threading.Thread(target=reconfigure_and_reduce, args=(i,))
              for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert all(c.mode() == "host" for c in comms)
        for i in range(2):
            np.testing.assert_allclose(outs[i]["g"], np.full(4, 3.0))
        hang.set()
        for c in comms:
            c.shutdown()

    def test_refuses_multi_process_runtime(self, monkeypatch):
        """VERDICT r2 missing #1: the in-process rendezvous is
        single-controller only; in a multi-controller job it must refuse
        construction loudly instead of silently hanging/degrading (see
        docs/design/cross_group_backend.md for why a process-spanning
        device path is not buildable on today's JAX)."""
        from torchft_tpu.backends import mesh as mesh_mod

        monkeypatch.setattr(mesh_mod.jax, "process_count", lambda: 4)
        with pytest.raises(RuntimeError, match="single-controller"):
            mesh_mod.MeshWorld(num_groups=2)

    def test_rendezvous_mismatch_fails_all_waiters_immediately(self):
        """ADVICE r2: a kind/world mismatch must fail EVERY contributor of
        the entry at once — the early arrivals' futures must not park
        until the timeout expires."""
        import time

        from torchft_tpu.backends.mesh import MeshWorld

        world = MeshWorld(num_groups=3, timeout_sec=30)
        early = world.contribute(("p", "op", 0), rank=0, world=3,
                                 kind="sum", payload=np.ones(2))
        late = world.contribute(("p", "op", 0), rank=1, world=2,
                                kind="sum", payload=np.ones(2))
        t0 = time.perf_counter()
        with pytest.raises(CommunicatorError, match="mismatch"):
            late.result(timeout=10)
        with pytest.raises(CommunicatorError, match="mismatch"):
            early.result(timeout=10)  # fails NOW, not after timeout_sec
        assert time.perf_counter() - t0 < 5

    def test_stale_epoch_cannot_crosstalk(self):
        """A straggler keyed on an old quorum prefix can never meet a new
        quorum's rendezvous — it expires instead of corrupting the sum."""
        world, comms = self.make_world(2, timeout=0.5)
        comms[0].configure("store/old", 0, 2)
        stale = comms[0].allreduce({"g": np.full(2, 100.0)})

        comms[0].configure("store/new", 0, 2)
        comms[1].configure("store/new", 1, 2)

        def run(rank):
            return comms[rank].allreduce(
                {"g": np.full(2, float(rank + 1))}).result(timeout=30)

        outs = []
        def go(r):
            outs.append((r, run(r)))
        ts = [threading.Thread(target=go, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        for _, out in outs:
            np.testing.assert_allclose(out["g"], np.full(2, 3.0))
        with pytest.raises(CommunicatorError):
            stale.result(timeout=10)

    def test_peer_shutdown_aborts_pending_immediately(self):
        """Mesh analogue of abort-by-socket-close: a peer's shutdown must
        fail in-flight rendezvous NOW, not after the timeout — otherwise
        a survivor sits out the lighthouse for the whole timeout and a
        rejoining peer cuts a solo quorum (split progress)."""
        import time as _time

        world, comms = self.make_world(2, timeout=60)
        for r in range(2):
            comms[r].configure("store/qd", r, 2)
        fut = comms[0].allreduce({"g": np.ones(2)})
        t0 = _time.monotonic()
        comms[1].shutdown()
        with pytest.raises(CommunicatorError, match="shut down"):
            fut.result(timeout=30)
        assert _time.monotonic() - t0 < 5  # way under the 60s timer

    def test_reconfigure_aborts_old_prefix_pending(self):
        world, comms = self.make_world(2, timeout=60)
        for r in range(2):
            comms[r].configure("store/q1", r, 2)
        fut = comms[0].allreduce({"g": np.ones(2)})
        comms[1].configure("store/q2", 0, 1)  # peer moves to a new quorum
        with pytest.raises(CommunicatorError, match="reconfigured away"):
            fut.result(timeout=30)


def _socketpair_rings(world):
    """Pre-wired rings over socketpairs: pair[i] connects rank i's
    next-hop to rank (i+1)%world's prev-hop. Exercises the REAL ring
    transport (sender thread, segmented receive) with no store
    rendezvous and no native library."""
    import socket as _socket

    from torchft_tpu.backends.host import _Ring

    pairs = [_socket.socketpair() for _ in range(world)]
    return [_Ring(pairs[r][0], pairs[(r - 1) % world][1],
                  _socket.socket())
            for r in range(world)]


class TestWireRingTransport:
    """The wire-dtype ring itself (backends/host.py _ring_allreduce_wire)
    over real sockets: one quantization per contribution, canonical-order
    f32 folds (cross-rank bitwise identity), the byte crossover fallback,
    and the send-side ring byte counter."""

    def _run(self, world, fn):
        rings = _socketpair_rings(world)
        comms = []
        for r in range(world):
            c = HostCommunicator(timeout_sec=15)
            c._rank, c._world = r, world
            comms.append(c)
        out = [None] * world
        errors = []

        def w(r):
            try:
                out[r] = fn(comms[r], rings[r], r)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        ts = [threading.Thread(target=w, args=(r,)) for r in range(world)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        alive = [t for t in ts if t.is_alive()]
        for ring in rings:
            ring.close()
        assert not alive, "wire ring deadlocked"
        assert not errors, errors
        return out, comms

    def test_world2_one_quantization_and_halved_bytes(self):
        import jax.numpy as jnp

        bf = np.dtype(jnp.bfloat16)
        rng = np.random.default_rng(0)
        x = [rng.normal(size=300_001).astype(np.float32)
             for _ in range(2)]
        q = [xi.astype(bf).astype(np.float32) for xi in x]

        out, comms = self._run(2, lambda c, ring, r: c._ring_allreduce_wire(
            ring, x[r].astype(bf), np.dtype(np.float32)))
        expected = q[0] + q[1]
        np.testing.assert_array_equal(out[0], expected)
        np.testing.assert_array_equal(out[1], expected)
        # Ring bytes: the full wire buffer once per rank — half the f32
        # bytes the exact ring would move at world 2.
        for c in comms:
            assert c.ring_bytes_total() == x[0].size * bf.itemsize
            c.shutdown()

    def test_world3_canonical_order_bitwise_across_ranks(self):
        import jax.numpy as jnp

        bf = np.dtype(jnp.bfloat16)
        rng = np.random.default_rng(1)
        x = [rng.normal(size=10_007).astype(np.float32) for _ in range(3)]
        q = [xi.astype(bf).astype(np.float32) for xi in x]

        out, comms = self._run(3, lambda c, ring, r: c._ring_allreduce_wire(
            ring, x[r].astype(bf), np.dtype(np.float32)))
        # Canonical rank-order fold: identical bits on every rank, equal
        # to the ascending-rank f32 sum of once-quantized contributions.
        np.testing.assert_array_equal(out[0], (q[0] + q[1]) + q[2])
        np.testing.assert_array_equal(out[1], out[0])
        np.testing.assert_array_equal(out[2], out[0])
        for c in comms:
            c.shutdown()

    def test_crossover_falls_back_to_exact_ring(self):
        """Past world*wire > 2*orig the raw-contribution form would cost
        MORE than the exact ring, so the buffer upcasts locally and takes
        the standard ring — numerics unchanged (quantization already
        happened at pack)."""
        import jax.numpy as jnp

        bf = np.dtype(jnp.bfloat16)
        x = np.linspace(-2, 2, 5_003).astype(np.float32)
        q = x.astype(bf).astype(np.float32)

        out, comms = self._run(5, lambda c, ring, r: c._ring_allreduce_wire(
            ring, x.astype(bf), np.dtype(np.float32)))
        for o in out:
            np.testing.assert_allclose(o, 5 * q, rtol=1e-5)
        # Exact-ring byte signature: ~2*(n-1)/n * f32 bytes per rank —
        # LESS than the (n-1) * wire bytes raw forwarding would cost at
        # this world size, which is exactly why it falls back.
        exact_bytes = 2 * 4 / 5 * x.size * 4
        gather_bytes = 4 * x.size * bf.itemsize
        for c in comms:
            sent = c.ring_bytes_total()
            assert abs(sent - exact_bytes) < 64  # chunk-boundary slack
            assert sent < gather_bytes
            c.shutdown()

    def test_do_allreduce_wire_mixes_exact_and_wire_chunks(self):
        import jax.numpy as jnp

        bf = np.dtype(jnp.bfloat16)
        rng = np.random.default_rng(2)
        x = [rng.normal(size=1_000).astype(np.float32) for _ in range(2)]
        q = [xi.astype(bf).astype(np.float32) for xi in x]
        ints = np.arange(7, dtype=np.int64)

        def fn(c, ring, r):
            return c._do_allreduce_wire(
                ring,
                [x[r].copy(), x[r].astype(bf), ints * (r + 1)],
                [np.dtype(np.float32), np.dtype(np.float32),
                 np.dtype(np.int64)],
                "sum")

        out, comms = self._run(2, fn)
        for o in out:
            np.testing.assert_array_equal(o[0], x[0] + x[1])  # exact
            np.testing.assert_array_equal(o[1], q[0] + q[1])  # wire
            np.testing.assert_array_equal(o[2], ints * 3)     # int exact
        for c in comms:
            c.shutdown()
