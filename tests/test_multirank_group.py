"""Multi-rank replica groups, end to end.

The reference's world: each replica group has ``world_size`` local ranks;
rank 0 hosts the group's manager server + store, every rank joins the
quorum and votes in the commit barrier, and each local-rank stratum forms
its own cross-group communicator ring (store prefix
``.../torchft/{quorum_id}/{local_rank}``). Elsewhere the suite uses
world_size=1 groups (one JAX process per slice); this file drives the
2-groups x 2-ranks topology the reference's manager protocol was built
for (manager.rs local-rank rendezvous, should_commit all-rank barrier)."""

from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from torchft_tpu import HostCommunicator, Lighthouse, Manager
from torchft_tpu._native import Store


@pytest.mark.integration
class TestMultiRankGroups:
    def test_two_groups_two_ranks_lockstep(self):
        n_groups, n_ranks, steps = 2, 2, 4
        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=n_groups,
                        join_timeout_ms=2000, quorum_tick_ms=20)
        stores = [Store() for _ in range(n_groups)]

        def worker(group: int, rank: int):
            m = Manager(
                comm=HostCommunicator(timeout_sec=15),
                load_state_dict=lambda s: None,
                state_dict=lambda: {},
                min_replica_size=n_groups,
                replica_id=f"mr{group}",
                lighthouse_addr=lh.address(),
                rank=rank,
                world_size=n_ranks,
                store_addr=stores[group].address(),
                timeout_ms=15_000,
                quorum_timeout_ms=15_000,
            )
            sums = []
            try:
                for _ in range(steps):
                    m.step()
                    # each (group, rank) contributes a distinct value; the
                    # ring averages across groups within the rank stratum
                    tree = {"g": np.full(
                        4, float(10 * group + rank), np.float32)}
                    fut = m.allreduce(tree)
                    out = fut.result(timeout=30)
                    assert m.should_commit(), \
                        f"group {group} rank {rank} failed commit"
                    sums.append(np.asarray(out["g"]).copy())
                return group, rank, sums, m.num_participants()
            finally:
                m.shutdown()

        try:
            with ThreadPoolExecutor(max_workers=n_groups * n_ranks) as pool:
                futs = [pool.submit(worker, g, r)
                        for g in range(n_groups) for r in range(n_ranks)]
                results = [f.result(timeout=180) for f in futs]
        finally:
            lh.shutdown()
            for s in stores:
                s.shutdown()

        for group, rank, sums, participants in results:
            assert participants == n_groups
            # Step 1 is the init-sync heal step (the non-primary of each
            # rank stratum contributes zeros while it heals); from step 2
            # on, the stratum mean is (rank + (10 + rank)) / 2 = rank + 5.
            # Ranks never mix across strata, groups always do.
            expected = np.full(4, rank + 5.0, np.float32)
            assert len(sums) == steps
            for got in sums[1:]:
                np.testing.assert_allclose(got, expected)

    def test_commit_barrier_spans_local_ranks(self):
        """A failure on ONE local rank must abort the commit for every
        rank of the group (reference manager.rs should_commit barrier:
        decision = no rank reported failure)."""
        n_ranks = 2
        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1,
                        join_timeout_ms=1000, quorum_tick_ms=20)
        store = Store()

        def worker(rank: int):
            m = Manager(
                comm=HostCommunicator(timeout_sec=15),
                load_state_dict=lambda s: None,
                state_dict=lambda: {},
                min_replica_size=1,
                replica_id="barrier",
                lighthouse_addr=lh.address(),
                rank=rank,
                world_size=n_ranks,
                store_addr=store.address(),
                timeout_ms=15_000,
                quorum_timeout_ms=15_000,
            )
            try:
                m.step()
                m.allreduce({"g": np.ones(2, np.float32)}).result(timeout=30)
                if rank == 1:
                    m.report_error(RuntimeError("injected device failure"))
                first = m.should_commit()
                # next step must recover: error resets, both commit
                m.step()
                m.allreduce({"g": np.ones(2, np.float32)}).result(timeout=30)
                second = m.should_commit()
                return rank, first, second
            finally:
                m.shutdown()

        try:
            with ThreadPoolExecutor(max_workers=n_ranks) as pool:
                futs = [pool.submit(worker, r) for r in range(n_ranks)]
                results = dict((r, (a, b)) for r, a, b in
                               (f.result(timeout=120) for f in futs))
        finally:
            lh.shutdown()
            store.shutdown()

        # the healthy rank 0 is dragged down by rank 1's error...
        assert results[0][0] is False and results[1][0] is False
        # ...and both recover the very next step
        assert results[0][1] is True and results[1][1] is True
