"""Tests for the ChaosNet fault injector (:mod:`torchft_tpu.chaos`):
schedule determinism, the ``TORCHFT_CHAOS`` spec grammar, socket/stream/
communicator wrappers, the chaos-hardened heal fetch — and the seeded
multi-group chaos soak (``slow``/``nightly``) asserting zero lost or
duplicated commits while every transport is being disrupted."""

import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from torchft_tpu import chaos
from torchft_tpu.chaos import (ChaosCommunicator, ChaosSchedule, Decision,
                               EndpointChaos, parse_spec)
from torchft_tpu.communicator import CommunicatorError, DummyCommunicator
from torchft_tpu.retry import RetryPolicy, RetryStats


import conftest

requires_native = conftest.requires_native()


class TestSchedule:
    def test_same_seed_same_trace(self):
        eps = {"ring": EndpointChaos(reset_rate=0.3, short_rate=0.2,
                                     latency_ms=1, jitter_ms=2)}
        a, b = ChaosSchedule(seed=9, endpoints=eps), \
            ChaosSchedule(seed=9, endpoints=eps)
        da = [a.decide("ring", "send") for _ in range(100)]
        db = [b.decide("ring", "send") for _ in range(100)]
        assert da == db
        assert any(d.fault for d in da)  # at these rates faults fired

    def test_different_seed_different_trace(self):
        eps = {"ring": EndpointChaos(reset_rate=0.3, jitter_ms=5)}
        a = [ChaosSchedule(seed=1, endpoints=eps).decide("ring", "send")
             for _ in range(50)]
        b = [ChaosSchedule(seed=2, endpoints=eps).decide("ring", "send")
             for _ in range(50)]
        assert a != b

    def test_channels_are_independent_streams(self):
        """Decision n of a channel is a pure function of (seed, channel,
        n): interleaving another channel's draws must not perturb it —
        the property that makes multi-threaded traces replayable."""
        eps = {"ring": EndpointChaos(reset_rate=0.3),
               "store": EndpointChaos(reset_rate=0.3)}
        solo = ChaosSchedule(seed=5, endpoints=eps)
        ring_solo = [solo.decide("ring", "send") for _ in range(40)]
        mixed = ChaosSchedule(seed=5, endpoints=eps)
        ring_mixed = []
        for i in range(40):
            mixed.decide("store", "get")  # interleaved foreign draws
            ring_mixed.append(mixed.decide("ring", "send"))
        assert ring_solo == ring_mixed

    def test_endpoint_fallback(self):
        s = ChaosSchedule(seed=0, endpoints={
            "ring": EndpointChaos(latency_ms=5),
            "*": EndpointChaos(latency_ms=1)})
        assert s.config_for("ring:3").latency_ms == 5
        assert s.config_for("store").latency_ms == 1
        s2 = ChaosSchedule(seed=0, endpoints={"ring": EndpointChaos()})
        assert s2.config_for("heal") is None
        assert s2.decide("heal", "fetch") is None

    def test_max_faults_cap(self):
        s = ChaosSchedule(seed=3, endpoints={
            "ring": EndpointChaos(reset_rate=1.0, max_faults=2)})
        faults = [s.decide("ring", "send").fault for _ in range(10)]
        assert faults[:2] == ["reset", "reset"]
        assert all(f is None for f in faults[2:])

    def test_trace_replay_reproduces(self):
        """The acceptance property: replaying a recorded per-channel op
        sequence through a fresh schedule with the same seed reproduces
        the identical injection trace."""
        eps = {"ring": EndpointChaos(reset_rate=0.2, short_rate=0.1,
                                     jitter_ms=3),
               "store": EndpointChaos(reset_rate=0.3)}
        s = ChaosSchedule(seed=11, endpoints=eps)
        for i in range(30):
            s.decide("ring", "send" if i % 2 else "recv")
            if i % 3 == 0:
                s.decide("store", "get")
        trace = s.trace()
        replay = ChaosSchedule(seed=11, endpoints=eps)
        for d in trace:
            replay.decide(d.endpoint, d.op)
        assert replay.trace() == trace


class TestSpecGrammar:
    def test_full_spec(self):
        s = parse_spec("seed=42;ring:reset_rate=0.02,latency_ms=5;"
                       "store:blackhole_rate=0.01,blackhole_ms=100;"
                       "*:jitter_ms=2;manager:max_faults=7")
        assert s.seed == 42
        assert s.endpoints["ring"].reset_rate == 0.02
        assert s.endpoints["ring"].latency_ms == 5
        assert s.endpoints["store"].blackhole_ms == 100
        assert s.endpoints["*"].jitter_ms == 2
        assert s.endpoints["manager"].max_faults == 7

    def test_empty_clauses_tolerated(self):
        s = parse_spec("seed=1;;ring:latency_ms=1;")
        assert s.seed == 1 and "ring" in s.endpoints

    @pytest.mark.parametrize("bad", [
        "ring",                       # no colon
        "ring:bogus_field=1",         # unknown field
        "ring:latency_ms",            # no value
    ])
    def test_bad_specs_raise(self, bad):
        with pytest.raises(ValueError):
            parse_spec(bad)

    def test_env_activation(self, monkeypatch):
        chaos.reset()  # re-arm env parsing (uninstall is sticky)
        monkeypatch.setenv("TORCHFT_CHAOS", "seed=5;ring:latency_ms=1")
        try:
            s = chaos.active()
            assert s is not None and s.seed == 5
            # parsed once, then cached
            assert chaos.active() is s
            # uninstall is STICKY against the env: the spec must NOT
            # silently re-arm on the next transport op (drain boundary).
            chaos.uninstall()
            assert chaos.active() is None
        finally:
            chaos.reset()

    def test_inactive_is_none(self, monkeypatch):
        chaos.reset()
        monkeypatch.delenv("TORCHFT_CHAOS", raising=False)
        try:
            assert chaos.active() is None
            sock = socket.socket()
            try:
                assert chaos.wrap_socket(sock, "ring") is sock
            finally:
                sock.close()
        finally:
            chaos.uninstall()


def _socketpair_with_chaos(schedule):
    a, b = socket.socketpair()
    a.settimeout(5)
    b.settimeout(5)
    return chaos.wrap_socket(a, "ring", schedule), b


class TestChaosSocket:
    def test_passthrough_when_clean(self):
        s = ChaosSchedule(seed=0, endpoints={"ring": EndpointChaos()})
        wrapped, peer = _socketpair_with_chaos(s)
        try:
            wrapped.sendall(b"hello")
            assert peer.recv(5) == b"hello"
            peer.sendall(b"world")
            buf = bytearray(5)
            assert wrapped.recv_into(memoryview(buf)) == 5
            assert bytes(buf) == b"world"
        finally:
            wrapped.close()
            peer.close()

    def test_reset_closes_both_ways(self):
        s = ChaosSchedule(seed=0, endpoints={
            "ring": EndpointChaos(reset_rate=1.0, max_faults=1)})
        wrapped, peer = _socketpair_with_chaos(s)
        try:
            with pytest.raises(ConnectionResetError, match="chaos"):
                wrapped.sendall(b"data")
            # the real socket was aborted, so the peer observes EOF/reset
            assert peer.recv(4) == b""
        finally:
            peer.close()

    def test_short_write_transfers_partial_then_resets(self):
        s = ChaosSchedule(seed=0, endpoints={
            "ring": EndpointChaos(short_rate=1.0, max_faults=1)})
        wrapped, peer = _socketpair_with_chaos(s)
        try:
            payload = b"x" * 1000
            with pytest.raises(ConnectionResetError, match="short write"):
                wrapped.sendall(payload)
            got = b""
            while True:
                part = peer.recv(4096)
                if not part:
                    break
                got += part
            assert 0 < len(got) < len(payload)  # genuinely partial
        finally:
            peer.close()

    def test_short_read_raises_after_partial_fill(self):
        s = ChaosSchedule(seed=0, endpoints={
            "ring": EndpointChaos(short_rate=1.0, max_faults=1)})
        wrapped, peer = _socketpair_with_chaos(s)
        try:
            peer.sendall(b"y" * 100)
            buf = bytearray(100)
            with pytest.raises(ConnectionResetError, match="short read"):
                wrapped.recv_into(memoryview(buf))
        finally:
            peer.close()

    def test_latency_delays_io(self):
        s = ChaosSchedule(seed=0, endpoints={
            "ring": EndpointChaos(latency_ms=30)})
        wrapped, peer = _socketpair_with_chaos(s)
        try:
            t0 = time.perf_counter()
            wrapped.sendall(b"z")
            assert (time.perf_counter() - t0) >= 0.025
            assert peer.recv(1) == b"z"
        finally:
            wrapped.close()
            peer.close()


class TestChaosCommunicator:
    def _scripted(self, fault, phase):
        class One(ChaosSchedule):
            def config_for(self, endpoint):
                return EndpointChaos()

            def decide(self, endpoint, op):
                return Decision(endpoint=endpoint, op=op, n=0,
                                delay_ms=0.0, fault=fault, phase=phase,
                                frac=0.5, blackhole_ms=0.0)

        return One(seed=0, endpoints={})

    def test_clean_forwarding(self):
        inner = DummyCommunicator()
        c = ChaosCommunicator(inner, ChaosSchedule(seed=0, endpoints={}))
        assert c.allreduce({"g": np.ones(2)}).result()["g"].sum() == 2
        assert inner.allreduce_count == 1
        assert c.size() == 1 and c.rank() == 0
        assert not c.wants_device_arrays

    def test_pre_fault_raises_sync(self):
        c = ChaosCommunicator(DummyCommunicator(),
                              self._scripted("reset", "pre"))
        with pytest.raises(CommunicatorError, match="chaos"):
            c.allreduce({"g": np.ones(2)})

    def test_post_fault_fails_future(self):
        c = ChaosCommunicator(DummyCommunicator(),
                              self._scripted("reset", "post"))
        fut = c.allreduce({"g": np.ones(2)})
        assert isinstance(fut.exception(), CommunicatorError)

    def test_fingerprint_and_shutdown_forward(self):
        inner = DummyCommunicator()
        c = ChaosCommunicator(inner, ChaosSchedule(seed=0, endpoints={}))
        c.set_allreduce_config_fingerprint("fp")
        assert inner.allreduce_config_fingerprint == "fp"
        c.configure("store:1/x", 0, 1)
        assert inner.configure_count == 1


class TestHealUnderChaos:
    """The heal transport end to end (pure Python, no native lib): a real
    CheckpointServer streams a pytree; chaos injects a mid-stream reset
    on the first fetch; the retry layer re-fetches and the restore
    succeeds."""

    def test_fetch_retries_mid_stream_reset(self):
        from torchft_tpu.checkpointing import CheckpointServer

        state = {"w": np.arange(64, dtype=np.float32),
                 "b": np.ones(8, dtype=np.float32)}
        srv = CheckpointServer(lambda: state, bind_host="127.0.0.1")
        srv.allow_checkpoint(1)
        fails = [2]  # first two read() calls of the body get faults

        class Script(ChaosSchedule):
            def config_for(self, endpoint):
                return EndpointChaos()

            def decide(self, endpoint, op):
                fault = None
                if op == "read" and fails[0] > 0:
                    fails[0] -= 1
                    fault = "reset"
                return Decision(endpoint=endpoint, op=op, n=0,
                                delay_ms=0.0, fault=fault, phase="pre",
                                frac=0.5, blackhole_ms=0.0)

        chaos.install(Script(seed=0, endpoints={}))
        try:
            stats = RetryStats()
            target = {"w": np.zeros(64, dtype=np.float32),
                      "b": np.zeros(8, dtype=np.float32)}
            out = CheckpointServer.load_from_address(
                srv.address(), target, device_put=False,
                retry_policy=RetryPolicy(max_attempts=4, base_delay_ms=1),
                retry_stats=stats)
            np.testing.assert_array_equal(out["w"], state["w"])
            np.testing.assert_array_equal(out["b"], state["b"])
            assert stats.snapshot()["retry_count"] == 2
        finally:
            chaos.uninstall()
            srv.shutdown()

    def test_fatal_refusal_does_not_retry(self):
        from torchft_tpu.checkpointing import CheckpointServer

        srv = CheckpointServer(lambda: {"w": np.ones(2)},
                               bind_host="127.0.0.1")
        srv.allow_checkpoint(3)
        try:
            stats = RetryStats()
            # Request a WRONG step: 400 "invalid checkpoint requested"
            # must surface immediately, not retry.
            bad = srv.address().rsplit("/", 1)[0] + "/99"
            with pytest.raises(Exception, match="[Ii]nvalid|400"):
                CheckpointServer.load_from_address(
                    bad, {"w": np.ones(2)}, device_put=False,
                    retry_policy=RetryPolicy(max_attempts=5,
                                             base_delay_ms=1),
                    retry_stats=stats)
            assert stats.snapshot()["retry_count"] == 0
        finally:
            srv.shutdown()


class TestDonorKill:
    """The donor-kill fault family: a killed endpoint hangs up its
    in-flight stream and refuses every later dial — the way a dead donor
    process behaves — deterministically (kill_after_bytes) or drawn from
    the seeded stream (kill_rate)."""

    def test_kill_rate_latches_endpoint_dead(self):
        sched = ChaosSchedule(
            seed=1, endpoints={"heal": EndpointChaos(kill_rate=1.0)})
        with pytest.raises(ConnectionResetError, match="died"):
            chaos.begin("heal:1.2.3.4:77", "dial", sched)
        assert sched.is_dead("heal:1.2.3.4:77")
        with pytest.raises(ConnectionRefusedError, match="refused"):
            chaos.begin("heal:1.2.3.4:77", "dial", sched)
        # a different donor has its own life
        assert not sched.is_dead("heal:5.6.7.8:99")
        sched.revive_endpoint("heal:1.2.3.4:77")
        assert not sched.is_dead("heal:1.2.3.4:77")

    def test_kill_after_bytes_hangs_up_mid_stream(self):
        import io

        sched = ChaosSchedule(
            seed=0,
            endpoints={"heal": EndpointChaos(kill_after_bytes=100)})
        reader = chaos.wrap_reader(io.BytesIO(bytes(300)), "heal:a:1",
                                   sched)
        got = b""
        with pytest.raises(ConnectionResetError, match="dead"):
            while True:
                part = reader.read(40)
                if not part:
                    break
                got += part
        # the packet crossing the threshold is still delivered; the NEXT
        # read hits the dead latch
        assert 100 <= len(got) <= 140
        assert sched.is_dead("heal:a:1")
        with pytest.raises(ConnectionRefusedError):
            chaos.begin("heal:a:1", "dial", sched)
        # an independent donor (own byte counter) still streams
        reader2 = chaos.wrap_reader(io.BytesIO(b"x" * 50), "heal:b:2",
                                    sched)
        assert reader2.read(50) == b"x" * 50

    def test_spec_parses_kill_fields(self):
        sched = parse_spec(
            "seed=3;heal:kill_rate=0.5,kill_after_bytes=1000000")
        cfg = sched.config_for("heal:any:1")
        assert cfg.kill_rate == 0.5
        assert cfg.kill_after_bytes == 1000000


class TestPoisonedRingRecovery:
    """A transient collective failure with UNCHANGED membership must not
    wedge the job: a latched CommunicatorError poisons the communicator
    and the next quorum round forces a rebuild onto the deterministic
    recovery prefix keyed by (quorum_id, max_step)."""

    def _make_manager(self, comm, client):
        from unittest.mock import MagicMock

        from torchft_tpu.manager import Manager

        return Manager(
            comm=comm, load_state_dict=MagicMock(),
            state_dict=lambda: {}, min_replica_size=1,
            use_async_quorum=False, rank=0, world_size=1,
            replica_id="poison", _manager_client=client)

    def _quorum(self, qid, max_step):
        from torchft_tpu._native import QuorumResult

        return QuorumResult(
            quorum_id=qid, recover_manager_address="m:1",
            store_address="s:1", max_step=max_step, max_rank=0,
            max_world_size=2, replica_rank=0, replica_world_size=2,
            heal=False)

    def test_comm_error_forces_recovery_rendezvous(self):
        from unittest.mock import MagicMock

        class Recording(DummyCommunicator):
            def __init__(self):
                super().__init__()
                self.prefixes = []

            def configure(self, store_addr, rank, world_size):
                super().configure(store_addr, rank, world_size)
                self.prefixes.append(store_addr)

        comm = Recording()
        client = MagicMock()
        client.quorum.return_value = self._quorum(qid=7, max_step=3)
        client.should_commit.return_value = False
        m = self._make_manager(comm, client)
        try:
            m.step()
            assert comm.prefixes == ["s:1/torchft/7/0"]
            # Transient ring failure: membership unchanged, ring dead.
            m.report_error(CommunicatorError("connection reset by peer"))
            assert not m.should_commit()
            m.step()  # same quorum id → recovery prefix, not a no-op
            assert comm.prefixes[-1] == "s:1/torchft/7.r3/0"
            # Poison cleared by the successful rebuild: the next same-
            # quorum round reconfigures nothing.
            client.should_commit.return_value = True
            assert m.should_commit()
            m.step()
            assert len(comm.prefixes) == 2
        finally:
            m.shutdown()

    def test_non_comm_error_does_not_rebuild_ring(self):
        from unittest.mock import MagicMock

        comm = DummyCommunicator()
        client = MagicMock()
        client.quorum.return_value = self._quorum(qid=5, max_step=2)
        client.should_commit.return_value = False
        m = self._make_manager(comm, client)
        try:
            m.step()
            assert comm.configure_count == 1
            # A quorum/heal-class error must NOT force a lone rebuild —
            # peers know nothing about it and their ring is healthy.
            m.report_error(RuntimeError("heal fetch failed"))
            assert not m.should_commit()
            m.step()
            assert comm.configure_count == 1
        finally:
            m.shutdown()

    def test_failed_recovery_keeps_poison_set(self):
        from unittest.mock import MagicMock

        class FailsOnce(DummyCommunicator):
            def __init__(self):
                super().__init__()
                self.prefixes = []
                self.fail_next = False

            def configure(self, store_addr, rank, world_size):
                self.prefixes.append(store_addr)
                if self.fail_next:
                    self.fail_next = False
                    raise CommunicatorError("rendezvous timeout")
                super().configure(store_addr, rank, world_size)

        comm = FailsOnce()
        client = MagicMock()
        client.quorum.return_value = self._quorum(qid=9, max_step=4)
        client.should_commit.return_value = False
        m = self._make_manager(comm, client)
        try:
            m.step()
            m.report_error(CommunicatorError("connection reset by peer"))
            assert not m.should_commit()
            comm.fail_next = True  # peers not at the rendezvous yet
            with pytest.raises(CommunicatorError):
                m.step()          # sync mode surfaces the failed round
            m.step()              # retried: poison still set → try again
            assert comm.prefixes[-2:] == ["s:1/torchft/9.r4/0",
                                          "s:1/torchft/9.r4/0"]
        finally:
            m.shutdown()


@requires_native
@pytest.mark.integration
@pytest.mark.slow
@pytest.mark.nightly
class TestChaosSoak:
    """The capstone: two replica groups run 20+ steps while a seeded
    schedule injects connection resets, latency/jitter, and short writes
    into EVERY transport — store, manager RPC, heal, host ring, and the
    allreduce path via the ChaosCommunicator shim. Oracles:

    * both groups finish all steps with bitwise-identical params;
    * zero lost or duplicated commits: no step is committed under two
      quorum ids, and ``batches_committed`` agrees across survivors;
    * faults actually fired on every targeted channel;
    * the same ``ChaosSchedule(seed)`` reproduces the identical
      injection trace when the recorded per-channel op sequence is
      replayed.
    """

    SEED = 1234

    def _schedule(self):
        # Hard-fault caps bound wall clock: every ring/allreduce fault
        # can cost one abort + a recovery rendezvous (up to ~timeout_sec
        # when a stalled peer must notice); manager/store faults are
        # cheap (absorbed by client retries in milliseconds).
        return ChaosSchedule(seed=self.SEED, endpoints={
            "ring": EndpointChaos(latency_ms=0.2, jitter_ms=1.0,
                                  reset_rate=0.01, short_rate=0.01,
                                  max_faults=4),
            "store": EndpointChaos(latency_ms=0.2, reset_rate=0.05,
                                   max_faults=6),
            "manager": EndpointChaos(jitter_ms=1.0, reset_rate=0.04,
                                     max_faults=8),
            "heal": EndpointChaos(reset_rate=0.2, max_faults=2),
            "allreduce": EndpointChaos(reset_rate=0.02, max_faults=2),
        })

    def test_soak_two_groups_no_lost_or_duplicated_commits(self):
        self._soak(overlap_steps=0)

    def test_soak_two_groups_overlap_mode(self):
        """The same seeded soak with the cross-step overlap engine
        (``overlap_steps=1``, docs/design/overlap.md): every fault now
        has a one-step-deferred commit in flight to corrupt, so the
        oracles additionally prove the deferred vote drops stale grads
        on every failure path — both groups still finish bitwise
        identical with zero lost or duplicated commits."""
        self._soak(overlap_steps=1)

    def test_soak_hier_leader_kill(self):
        """The hierarchical round (docs/design/hier_transport.md): 4
        groups as 2 simulated hosts x 2 co-located ranks run the same
        seeded chaos soak over the two-level ring, PLUS a hard leader
        kill mid-run (its star + leader-ring sockets dropped mid-op).
        A dead leader must latch a clean CommunicatorError and recover
        through the identical poison -> recovery-rendezvous ->
        re-election path as a flat ring reset: every group finishes
        every step, params bitwise identical, zero lost or duplicated
        commits."""
        results = self._soak(overlap_steps=0, n_groups=4,
                             hier_hosts=2, leader_kill_at=8)
        topos = [r.get("ring_topology", "") for r in results]
        assert any(t.startswith("hier:") for t in topos), topos

    def _soak(self, overlap_steps: int, n_groups: int = 2,
              hier_hosts=None, leader_kill_at=None):
        import jax
        import jax.numpy as jnp
        import optax

        from torchft_tpu import HostCommunicator, Lighthouse, Manager
        from torchft_tpu.models import MLP
        from torchft_tpu.parallel import FTTrainer

        # Chaotic phase through step `chaos_until`, then a clean drain to
        # `total_steps`: a fault landing exactly on the final step would
        # let one group commit it while the other exits with it aborted —
        # a legitimate at-most-one-step divergence the heal would repair
        # on the NEXT step, which never comes. The drain gives every
        # in-flight recovery (ring rebuild, heal catch-up) steps to
        # converge, so the end-state oracles are exact.
        total_steps = 24
        chaos_until = 18
        schedule = self._schedule()
        chaos.install(schedule)
        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1,
                        join_timeout_ms=1000, quorum_tick_ms=50)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 8)).astype(np.float32)
        y = (x.sum(axis=1) > 0).astype(np.int32)
        model = MLP(features=(16,), num_classes=2)

        def loss_fn(params, batch):
            logits = model.apply(params, batch["x"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["y"]).mean()

        progress = {}  # group -> latest step (read by the main thread)
        host_comms = {}  # group -> HostCommunicator (leader-kill hook)

        def make_host_comm(group: int) -> HostCommunicator:
            if hier_hosts:
                hc = HostCommunicator(
                    timeout_sec=15, hier=True,
                    host_id=f"soakh{group % hier_hosts}")
            else:
                hc = HostCommunicator(timeout_sec=15)
            host_comms[group] = hc
            return hc

        def run_group(group: int):
            params = model.init(jax.random.key(42), jnp.zeros((1, 8)))
            trainer = FTTrainer(
                loss_fn=loss_fn, tx=optax.sgd(0.05), params=params,
                manager_factory=lambda load, save: Manager(
                    # schedule=None: the shim reads chaos.active() per
                    # op, so the main thread's uninstall() at the drain
                    # boundary silences this path too.
                    comm=ChaosCommunicator(make_host_comm(group)),
                    load_state_dict=load, state_dict=save,
                    min_replica_size=1, replica_id=f"chaos{group}",
                    lighthouse_addr=lh.address(), rank=0, world_size=1,
                    timeout_ms=15_000, quorum_timeout_ms=15_000,
                    max_consecutive_failures=100,
                    overlap_steps=overlap_steps,
                ),
            )
            commits = []
            b = {"x": x[:16], "y": y[:16]}
            try:
                first = True
                while trainer.manager.current_step() < total_steps:
                    progress[group] = trainer.manager.current_step()
                    # Overlap mode settles the PREVIOUS step inside this
                    # call, so the (step, quorum, participants) triple a
                    # commit belongs to is the one in effect BEFORE
                    # step() advances (reading any of them after
                    # train_step would describe the NEXT step's quorum).
                    prev = (trainer.manager.current_step(),
                            trainer.manager.quorum_id(),
                            trainer.manager.num_participants())
                    _, committed = trainer.train_step(b)
                    if overlap_steps:
                        if committed and not first:
                            commits.append(prev)
                        first = False
                    elif committed:
                        commits.append(
                            (trainer.manager.current_step(),
                             trainer.manager.quorum_id(),
                             trainer.manager.num_participants()))
                # Overlap mode: settle the final in-flight step BEFORE
                # snapshotting params, or the oracle would compare
                # boundary states one update apart.
                final = trainer.flush()
                if overlap_steps and final:
                    commits.append(
                        (trainer.manager.current_step(),
                         trainer.manager.quorum_id(),
                         trainer.manager.num_participants()))
                return {
                    "params": jax.device_get(trainer.params),
                    "step": trainer.manager.current_step(),
                    "batches_committed":
                        trainer.manager.batches_committed(),
                    "commits": commits,
                    "metrics": trainer.manager.metrics(),
                    "ring_topology": trainer.manager.metrics_info()
                    .get("ring_topology", "flat"),
                }
            finally:
                trainer.shutdown()

        killed = [False]
        try:
            with ThreadPoolExecutor(max_workers=n_groups) as pool:
                futs = [pool.submit(run_group, g)
                        for g in range(n_groups)]
                # Drain boundary: once every group is past `chaos_until`,
                # stop injecting and let the tail converge cleanly.
                deadline = time.monotonic() + 480
                while not (len(progress) == n_groups and all(
                        s >= chaos_until for s in progress.values())):
                    if time.monotonic() > deadline:
                        break  # let result() surface the real failure
                    if any(f.done() and f.exception() for f in futs):
                        break
                    # The leader kill: once every group is past the
                    # kill step, drop one elected leader's hier sockets
                    # mid-flight — the next wire op on any survivor
                    # latches a CommunicatorError and the recovery
                    # rendezvous must rebuild + re-elect.
                    if (leader_kill_at is not None and not killed[0]
                            and len(progress) == n_groups
                            and all(s >= leader_kill_at
                                    for s in progress.values())):
                        for hc in host_comms.values():
                            topo = hc._hier
                            if topo is not None and topo.is_leader:
                                topo.close()
                                killed[0] = True
                                break
                    time.sleep(0.25)
                chaos.uninstall()
                results = [f.result(timeout=600) for f in futs]
        finally:
            chaos.uninstall()
            lh.shutdown()
        if leader_kill_at is not None:
            assert killed[0], "leader kill never fired"

        # Everyone finished every step under sustained disruption.
        assert all(r["step"] == total_steps for r in results), results
        # Zero duplicated commits: no step committed under two quorums.
        step_qids: dict = {}
        for r in results:
            for step, qid, _ in r["commits"]:
                step_qids.setdefault(step, set()).add(qid)
        split = {s: q for s, q in step_qids.items() if len(q) > 1}
        assert not split, f"steps committed under multiple quorums: {split}"
        # Zero lost commits: batches_committed consistent across
        # survivors, and params bitwise identical (a lost commit on one
        # side would diverge both).
        for r in results[1:]:
            assert (results[0]["batches_committed"]
                    == r["batches_committed"]), results
            jax.tree_util.tree_map(
                lambda a, b_: np.testing.assert_array_equal(a, b_),
                results[0]["params"], r["params"])

        # Chaos genuinely fired into the transports...
        trace = schedule.trace()
        channels_faulted = {d.endpoint.split(":", 1)[0]
                            for d in trace if d.fault is not None}
        assert {"store", "manager"} <= channels_faulted, channels_faulted
        # ...and the retry layer absorbed transient RPC faults (visible
        # in metrics rather than as training-loop crashes).
        total_retries = sum(r["metrics"]["retry_count"] for r in results)
        assert total_retries >= 1, [r["metrics"] for r in results]

        # Determinism: replaying the recorded per-channel op sequence
        # through a fresh ChaosSchedule(seed) reproduces the identical
        # injection trace.
        replay = self._schedule()
        for d in trace:
            replay.decide(d.endpoint, d.op)
        assert replay.trace() == trace
        return results


@pytest.mark.slow
@pytest.mark.nightly
@pytest.mark.heal_soak
class TestHealSoak:
    """Seeded chaos soak of repeated heals with donor churn
    (``scripts/test.sh heal-soak``; also rides the nightly tier): every
    round the primary donor is killed mid-stream at a deterministic byte
    offset while resets/short-reads pepper the heal channel. Every heal
    must complete with bitwise-identical state by FAILING OVER and
    RESUMING — the retry traffic must stay well under
    restart-from-byte-0 cost."""

    ROUNDS = 6

    def test_repeated_heals_with_donor_churn(self):
        import urllib.parse

        from torchft_tpu.checkpointing import CheckpointServer
        from torchft_tpu.serialization import plan_pytree

        total_resent = 0.0
        total_payload = 0.0
        for seed in range(self.ROUNDS):
            rng = np.random.RandomState(seed)
            state = {f"w{i}": rng.rand(2048).astype(np.float32)
                     for i in range(6)}
            donors_srv = [
                CheckpointServer(lambda s=state: s, bind_host="127.0.0.1")
                for _ in range(2)
            ]
            for srv in donors_srv:
                srv.allow_checkpoint(1)
            payload = plan_pytree(state).total_len
            netloc_a = urllib.parse.urlparse(
                donors_srv[0].address()).netloc
            kill_at = int(payload * (0.3 + 0.4 * rng.rand()))
            sched = ChaosSchedule(seed=seed, endpoints={
                "heal": EndpointChaos(reset_rate=0.02, short_rate=0.02),
                f"heal:{netloc_a}": EndpointChaos(
                    reset_rate=0.02, short_rate=0.02,
                    kill_after_bytes=kill_at),
            })
            chaos.install(sched)
            try:
                stats = {}
                out = CheckpointServer.load_from_address(
                    donors_srv[0].address(), state, device_put=False,
                    stats=stats,
                    retry_policy=RetryPolicy(max_attempts=8,
                                             base_delay_ms=1.0,
                                             jitter=0.0),
                    stall_timeout_sec=10,
                    donors=lambda i: donors_srv[1].address())
                for key, arr in state.items():
                    assert out[key].tobytes() == arr.tobytes(), (
                        f"round {seed}: leaf {key} not bitwise identical")
                assert stats["donor_failovers"] == 1, (seed, stats)
                assert stats["bytes_resumed"] < stats["payload_bytes"], (
                    seed, stats)
                total_resent += stats["bytes_resumed"]
                total_payload += stats["payload_bytes"]
            finally:
                chaos.uninstall()
                for srv in donors_srv:
                    srv.shutdown()
        # Across the soak, resume must beat restart-from-zero by a wide
        # margin: donors die mid-transfer every round, yet the re-sent
        # traffic stays under one payload's worth per round on average.
        assert total_resent < total_payload, (total_resent, total_payload)

    def test_striped_heal_rounds_with_donor_death(self):
        """Striped rounds (docs/design/sharded_update.md): every round
        the healer stripes one heal across 3 live donors and chaos kills
        one NON-manifest donor at a deterministic mid-stripe byte
        offset. The dead donor's remaining stripe must reassign to the
        survivors — committed leaves stay committed (bytes_resumed <
        payload), final state bitwise identical."""
        import random as _random
        import urllib.parse

        from torchft_tpu.checkpointing import CheckpointServer
        from torchft_tpu.serialization import plan_pytree

        total_resent = 0.0
        total_payload = 0.0
        for seed in range(self.ROUNDS):
            rng = np.random.RandomState(100 + seed)
            state = {f"w{i}": rng.rand(4096).astype(np.float32)
                     for i in range(9)}
            donors_srv = [
                CheckpointServer(lambda s=state: s, bind_host="127.0.0.1")
                for _ in range(3)
            ]
            for srv in donors_srv:
                srv.allow_checkpoint(1)
            addrs = [srv.address() for srv in donors_srv]
            payload = plan_pytree(state).total_len
            # Replicate the healer's seed-shuffle so the chaos kill lands
            # on a donor that is NOT serving the manifest (stripe[0]) —
            # the manifest donor dying is the failover path the legacy
            # soak above already covers.
            shuffled = list(dict.fromkeys(addrs))
            _random.Random(seed).shuffle(shuffled)
            victim = urllib.parse.urlparse(shuffled[1]).netloc
            kill_at = int((payload / 3) * (0.2 + 0.5 * rng.rand()))
            sched = ChaosSchedule(seed=seed, endpoints={
                f"heal:{victim}": EndpointChaos(
                    kill_after_bytes=kill_at),
            })
            chaos.install(sched)
            try:
                stats = {}
                out = CheckpointServer.load_from_address(
                    addrs[0], state, device_put=False, stats=stats,
                    retry_policy=RetryPolicy(max_attempts=8,
                                             base_delay_ms=1.0,
                                             jitter=0.0),
                    stall_timeout_sec=10,
                    donor_addrs=addrs, stripe_seed=seed)
                for key, arr in state.items():
                    assert out[key].tobytes() == arr.tobytes(), (
                        f"round {seed}: leaf {key} not bitwise identical")
                assert stats["stripe_donor_deaths"] >= 1, (seed, stats)
                assert stats["bytes_resumed"] < stats["payload_bytes"], (
                    seed, stats)
                total_resent += stats["bytes_resumed"]
                total_payload += stats["payload_bytes"]
            finally:
                chaos.uninstall()
                for srv in donors_srv:
                    srv.shutdown()
        # Only the dead donor's remaining stripe re-fetches: across the
        # soak the re-sent traffic must stay well under one full payload
        # per round (restart-from-zero would be >= ROUNDS * payload).
        assert total_resent < total_payload / 2, (
            total_resent, total_payload)
