"""Cross-replica sharding tests (docs/design/sharded_update.md;
``scripts/test.sh shard``): the ZeRO-style reduce-scatter weight update
(transport numerics, wrapper forwarding, Manager pipeline, FTOptimizer
stripe apply), the torrent-striped multi-donor heal, and the sharded
durable checkpoint format. All tier-1 — socketpair rings and real HTTP
on loopback, no native library."""

import os
import threading
import urllib.parse
import urllib.request
from unittest.mock import MagicMock, patch

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from test_manager import (_make_test_rings, _wired_comm, make_manager,
                          quorum_result)
from torchft_tpu import chaos
from torchft_tpu.backends.host import HostCommunicator, _Ring
from torchft_tpu.chaos import ChaosSchedule, EndpointChaos
from torchft_tpu.checkpointing import CheckpointServer
from torchft_tpu.communicator import (Communicator, _slice_shards,
                                      shard_bounds)
from torchft_tpu.manager import ShardedGrads, _stripe_seed
from torchft_tpu.optim import FTOptimizer

pytestmark = pytest.mark.shard


class _Holder:
    """Minimal FTOptimizer holder (the trainer duck type)."""

    def __init__(self, params, opt_state=None):
        self.params = params
        self.opt_state = opt_state


# ----------------------------------------------------------- geometry

class TestShardBounds:
    def test_partition_covers_exactly(self):
        for size in (0, 1, 7, 100, 101):
            for world in (1, 2, 3, 5, 8):
                b = shard_bounds(size, world)
                assert b[0] == 0 and b[-1] == size
                assert all(b[i] <= b[i + 1] for i in range(world))

    def test_slice_shards_concat_roundtrip(self):
        x = np.arange(103, dtype=np.float32)
        world = 4
        parts = [_slice_shards([x], r, world)[0] for r in range(world)]
        np.testing.assert_array_equal(np.concatenate(parts), x)
        # Copies, not views: callers own the shards outright.
        parts[0][:] = -1
        assert x[0] == 0

    def test_same_geometry_as_exact_ring_chunking(self):
        # The ONE-geometry invariant: the exact ring reduce-scatter's
        # stripe must equal shard_bounds' stripe, or reassembled params
        # tear at seams.
        b = shard_bounds(1000, 3)
        np.testing.assert_array_equal(
            b, np.linspace(0, 1000, 4, dtype=np.int64))


# ----------------------------------------------- transport numerics

def _run_ring(world, fn):
    rings = _make_test_rings(world)
    comms = []
    for r in range(world):
        c = HostCommunicator(timeout_sec=15)
        c._rank, c._world = r, world
        comms.append(c)
    out = [None] * world
    errors = []

    def w(r):
        try:
            out[r] = fn(comms[r], rings[r], r)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    ts = [threading.Thread(target=w, args=(r,)) for r in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    alive = [t for t in ts if t.is_alive()]
    for ring in rings:
        ring.close()
    assert not alive, "ring deadlocked"
    assert not errors, errors
    return out, comms


class TestReduceScatterWireTransport:
    """``_do_reduce_scatter_wire`` over real sockets: concat of every
    rank's stripe must be BITWISE identical to ``_do_allreduce_wire`` —
    the invariant making the ZeRO update's allgathered params equal the
    allreduce path's."""

    @pytest.mark.parametrize("world", [2, 3, 5])
    def test_exact_bitwise_vs_allreduce(self, world):
        rng = np.random.default_rng(world)
        x = [rng.normal(size=10_007).astype(np.float32)
             for _ in range(world)]
        ar, _ = _run_ring(world, lambda c, ring, r: c._do_allreduce_wire(
            ring, [x[r].copy()], [np.dtype(np.float32)], "sum"))
        rs, _ = _run_ring(
            world, lambda c, ring, r: c._do_reduce_scatter_wire(
                ring, [x[r].copy()], [np.dtype(np.float32)], "sum"))
        full = np.concatenate([rs[r][0] for r in range(world)])
        np.testing.assert_array_equal(full, ar[0][0])
        b = shard_bounds(x[0].size, world)
        for r in range(world):
            assert rs[r][0].size == int(b[r + 1] - b[r])

    @pytest.mark.parametrize("world", [2, 3, 5])
    def test_bf16_wire_bitwise_vs_allreduce(self, world):
        bf = np.dtype(jnp.bfloat16)
        rng = np.random.default_rng(10 + world)
        x = [rng.normal(size=10_007).astype(np.float32).astype(bf)
             for _ in range(world)]
        ar, _ = _run_ring(world, lambda c, ring, r: c._do_allreduce_wire(
            ring, [x[r].copy()], [np.dtype(np.float32)], "sum"))
        rs, _ = _run_ring(
            world, lambda c, ring, r: c._do_reduce_scatter_wire(
                ring, [x[r].copy()], [np.dtype(np.float32)], "sum"))
        full = np.concatenate([rs[r][0] for r in range(world)])
        np.testing.assert_array_equal(full, ar[0][0])

    def test_ring_byte_accounting(self):
        # Exact reduce-scatter = the ring's reduce-scatter phase + one
        # ownership-shift hop = 1.0*payload per rank, vs the allreduce's
        # 2(n-1)/n: equal at world 2, strictly fewer from world 3 on.
        # The wire path at world 2 exchanges only the peer's raw stripe:
        # half of allreduce_wire's full-buffer hop.
        x = np.ones(99_999, np.float32)
        for world in (2, 3):
            _, ar = _run_ring(
                world, lambda c, ring, r: c._do_allreduce_wire(
                    ring, [x.copy()], [np.dtype(np.float32)], "sum"))
            _, rs = _run_ring(
                world, lambda c, ring, r: c._do_reduce_scatter_wire(
                    ring, [x.copy()], [np.dtype(np.float32)], "sum"))
            assert abs(rs[0].ring_bytes_total() - x.nbytes) < 64
            want = 2 * (world - 1) / world * x.nbytes
            assert abs(ar[0].ring_bytes_total() - want) < 64
        bf = np.dtype(jnp.bfloat16)
        xb = x.astype(bf)
        _, arw = _run_ring(2, lambda c, ring, r: c._do_allreduce_wire(
            ring, [xb.copy()], [np.dtype(np.float32)], "sum"))
        _, rsw = _run_ring(
            2, lambda c, ring, r: c._do_reduce_scatter_wire(
                ring, [xb.copy()], [np.dtype(np.float32)], "sum"))
        assert abs(rsw[0].ring_bytes_total()
                   - arw[0].ring_bytes_total() / 2) < 4

    def test_mean_op_divides_stripe(self):
        x = np.full(1000, 3.0, np.float32)
        rs, _ = _run_ring(2, lambda c, ring, r: c._do_reduce_scatter_wire(
            ring, [x.copy()], [np.dtype(np.float32)], "mean"))
        np.testing.assert_array_equal(
            np.concatenate([rs[0][0], rs[1][0]]), np.full(1000, 3.0))


# ------------------------------------------------- wrapper contracts

class _RecordingComm(Communicator):
    """Fake inner comm recording reduce_scatter_wire forwarding."""

    def __init__(self, world=2, rank=0, fail=False):
        self._world, self._rank = world, rank
        self._fail = fail
        self.calls = []

    def configure(self, store_addr, rank, world_size):
        pass

    def allreduce(self, tree, op="sum"):
        from torchft_tpu.manager import _instant
        return _instant(tree)

    def allreduce_wire(self, buffers, orig_dtypes, op="sum"):
        raise AssertionError(
            "wrapper fell back to allreduce_wire instead of forwarding")

    def reduce_scatter_wire(self, buffers, orig_dtypes, op="sum"):
        from torchft_tpu.manager import _instant
        self.calls.append(("rs", len(list(buffers)), op))
        if self._fail:
            raise RuntimeError("boom")
        return _instant(_slice_shards(
            [np.ravel(np.asarray(b)).astype(d)
             for b, d in zip(buffers, orig_dtypes)],
            self._rank, self._world))

    def broadcast(self, tree, root=0):
        from torchft_tpu.manager import _instant
        return _instant(tree)

    def allgather(self, tree):
        from torchft_tpu.manager import _instant
        return _instant([tree] * self._world)

    def barrier(self):
        from torchft_tpu.manager import _instant
        return _instant(None)

    def rank(self):
        return self._rank

    def size(self):
        return self._world

    def shutdown(self):
        pass


class TestWrapperContracts:
    def test_default_impl_slices_allreduce_wire(self):
        # The ABC default must produce exactly this rank's stripe of the
        # allreduce_wire result — correctness floor for any backend that
        # has not specialized reduce_scatter_wire.
        class Base(_RecordingComm):
            def allreduce_wire(self, buffers, orig_dtypes, op="sum"):
                from torchft_tpu.manager import _instant
                return _instant([
                    np.ravel(np.asarray(b)).astype(d) * self._world
                    for b, d in zip(buffers, orig_dtypes)])

            reduce_scatter_wire = Communicator.reduce_scatter_wire

        c = Base(world=2, rank=1)
        out = c.reduce_scatter_wire(
            [np.arange(10, dtype=np.float32)], ["float32"]).result()
        b = shard_bounds(10, 2)
        np.testing.assert_array_equal(
            out[0], np.arange(10, dtype=np.float32)[b[1]:b[2]] * 2)

    def test_error_swallowing_forwards_and_latches(self):
        from torchft_tpu.communicator import ErrorSwallowingCommunicator

        inner = _RecordingComm(world=2, rank=1)
        c = ErrorSwallowingCommunicator(inner)
        out = c.reduce_scatter_wire(
            [np.ones(10, np.float32)], ["float32"]).result()
        assert inner.calls == [("rs", 1, "sum")]
        assert out[0].size == 5
        # A raising inner call latches and falls back to the stripe-
        # shaped structure-only default.
        inner2 = _RecordingComm(world=2, rank=1, fail=True)
        c2 = ErrorSwallowingCommunicator(inner2)
        out = c2.reduce_scatter_wire(
            [np.ones(10, np.float32)], ["float32"]).result()
        assert c2.error() is not None
        assert out[0].size == 5  # stripe geometry survives the error

    def test_managed_forwards_with_inner_geometry(self):
        from torchft_tpu.communicator import ManagedCommunicator

        inner = _RecordingComm(world=2, rank=1)
        mgr = MagicMock()
        mgr.errored.return_value = None
        mgr._comm = inner  # ManagedCommunicator reads the manager's comm
        c = ManagedCommunicator(mgr)
        out = c.reduce_scatter_wire(
            [np.ones(10, np.float32)], ["float32"]).result()
        assert inner.calls == [("rs", 1, "sum")]
        assert out[0].size == 5

    def test_chaos_forwards_on_own_stream(self):
        inner = _RecordingComm(world=2, rank=0)
        from torchft_tpu.chaos import ChaosCommunicator
        sched = ChaosSchedule(seed=1, endpoints={})
        c = ChaosCommunicator(inner, sched)
        c.reduce_scatter_wire(
            [np.ones(4, np.float32)], ["float32"]).result()
        assert inner.calls == [("rs", 1, "sum")]


# ----------------------------------------- Manager reduce_scatter

def _run_managers(world, body, mkw=None, heal_ranks=(),
                  echo_vote=False):
    """World thread-ranks, wired rings, mocked control plane; ``body``
    runs per rank with its Manager and returns that rank's result."""
    rings = _make_test_rings(world)
    out = [None] * world
    errors = []

    def run(rank):
        client = MagicMock()
        heal = rank in heal_ranks
        client.quorum.return_value = quorum_result(
            max_rank=(None if heal else rank),
            max_world_size=world - len(heal_ranks),
            replica_rank=rank, replica_world_size=world, heal=heal)
        if echo_vote:
            client.should_commit.side_effect = \
                lambda **kw: kw["should_commit"]
        else:
            client.should_commit.return_value = True
        m = make_manager(client, comm=_wired_comm(rings[rank], rank, world),
                         min_replica_size=world - len(heal_ranks),
                         **(mkw or {}))
        try:
            out[rank] = body(m, rank)
        except Exception as e:  # noqa: BLE001
            import traceback
            traceback.print_exc()
            errors.append(e)
        finally:
            m.shutdown()

    state = {"user": {}, "torchft": {"step": 1, "batches_committed": 0}}
    cp = patch("torchft_tpu.manager.CheckpointServer.load_from_address",
               return_value=state)
    pc = patch("torchft_tpu.manager.ManagerClient")
    with cp, pc:
        ts = [threading.Thread(target=run, args=(r,))
              for r in range(world)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        alive = [t for t in ts if t.is_alive()]
    for r in rings:
        r.close()
    assert not alive, "manager rig deadlocked"
    assert not errors, errors
    return out


GRADS = {
    "a": np.random.default_rng(0).normal(size=(257, 3)).astype(np.float32),
    "b": np.random.default_rng(1).normal(size=(1000,)).astype(np.float32),
}


class TestManagerReduceScatter:
    @pytest.mark.parametrize("wire", [None, "bf16"])
    def test_stripes_concat_to_allreduce_result(self, wire):
        mkw = {"allreduce_bucket_bytes": 1024}
        if wire == "bf16":
            mkw["allreduce_wire_dtype"] = jnp.bfloat16

        def tf(rank):
            return jax.tree_util.tree_map(
                lambda a: jnp.asarray(a) * (rank + 1), GRADS)

        def ar_body(m, rank):
            m.step()
            got = m.allreduce(tf(rank)).result(timeout=30)
            assert m.errored() is None, m.errored()
            return jax.tree_util.tree_map(np.asarray, got)

        def rs_body(m, rank):
            m.step()
            sg = m.reduce_scatter(tf(rank)).result(timeout=30)
            assert m.errored() is None, m.errored()
            assert isinstance(sg, ShardedGrads)
            assert m.metrics()["reduce_scatter_count"] == 1
            return sg

        ar = _run_managers(2, ar_body, mkw)
        rs = _run_managers(2, rs_body, mkw)
        # Reassemble the flat chunks from both ranks' stripes and
        # compare to the allreduce leaves, chunk by chunk.
        leaves_ar = jax.tree_util.tree_leaves(ar[0])
        for k, c in enumerate(rs[0].chunks):
            full = np.concatenate([np.asarray(rs[r].shards[k])
                                   for r in range(2)])
            want = np.concatenate([
                np.ravel(np.asarray(leaves_ar[i])) for i in c.idx])
            np.testing.assert_array_equal(full, want)

    def test_healer_gets_zero_contribution_stripe(self):
        # Rank 1 heals: contributes zeros but still receives its stripe
        # of the participants' average — the same flow the allreduce
        # path guarantees, striped.
        def body(m, rank):
            m.step()
            g = {"g": jnp.asarray(GRADS["b"])} if rank == 0 else \
                {"g": jnp.zeros_like(jnp.asarray(GRADS["b"]))}
            sg = m.reduce_scatter(g).result(timeout=30)
            assert m.errored() is None, m.errored()
            return sg

        out = _run_managers(2, body, heal_ranks=(1,))
        full = np.concatenate([np.asarray(out[r].shards[0])
                               for r in range(2)])
        # Participant world is 1: rank 0's grads unscaled, on BOTH.
        np.testing.assert_array_equal(full, GRADS["b"])

    def test_latched_error_drops_update_bitwise(self):
        """Ring death mid reduce-scatter: the error latches, the future
        resolves to the zero-stripe structural default, the vote aborts,
        and the holder's params (and stripe optimizer state) are
        UNTOUCHED — the sync path's drop semantics."""
        def body(m, rank):
            m.step()
            m.wait_quorum()
            # Kill the ring under the collective: both ranks' sockets
            # die, the comm worker raises, wrap_future swallows.
            m._comm._ring.close()
            tx = optax.adam(1e-2)
            opt = FTOptimizer(m, tx, jit=False)
            h = _Holder(jax.tree_util.tree_map(jnp.asarray, GRADS))
            p0 = jax.tree_util.tree_map(np.asarray, h.params)
            sg = m.reduce_scatter(
                jax.tree_util.tree_map(jnp.asarray, GRADS)).result(
                    timeout=30)
            assert m.errored() is not None
            assert isinstance(sg, ShardedGrads)  # geometry survives
            assert all(not np.any(np.asarray(s)) for s in sg.shards)
            committed = opt.apply(h, sg)
            assert committed is False
            for k in GRADS:
                np.testing.assert_array_equal(
                    np.asarray(h.params[k]), p0[k])
            assert opt._shard_state is None  # no stripe state committed
            assert m.metrics()["aborted_steps"] == 1
            return True

        out = _run_managers(
            2, body, {"shard_update": True}, echo_vote=True)
        assert out == [True, True]


# ------------------------------------------------ optimizer E2E

class TestShardedOptimizerE2E:
    """Full loop: reduce_scatter -> stripe adam update -> allgather ->
    reassemble, bitwise vs the sync allreduce+full-update path."""

    P0 = {"w": np.random.default_rng(7).normal(size=(37, 5)).astype(
        np.float32),
        "b": np.random.default_rng(8).normal(size=(113,)).astype(
            np.float32)}

    def _train(self, world, shard, steps, wire=None):
        rng = np.random.default_rng(42)
        grads = [[{k: rng.normal(size=v.shape).astype(np.float32)
                   for k, v in self.P0.items()}
                  for _ in range(world)] for _ in range(steps)]

        def body(m, rank):
            tx = optax.adam(1e-2)
            opt = FTOptimizer(m, tx, jit=False)
            h = _Holder(jax.tree_util.tree_map(jnp.asarray, self.P0),
                        None if shard else tx.init(self.P0))
            for s in range(steps):
                m.step()
                g = jax.tree_util.tree_map(
                    lambda a: jnp.asarray(a) * (rank + 1), grads[s][rank])
                fut = (m.reduce_scatter(g) if shard else m.allreduce(g))
                assert opt.apply(h, fut.result(timeout=30))
                assert m.errored() is None, m.errored()
            return {"params": jax.tree_util.tree_map(np.asarray, h.params),
                    "state_bytes": opt.shard_state_bytes(),
                    "metrics": m.metrics()}

        mkw = {"allreduce_bucket_bytes": 512, "shard_update": shard}
        if wire is not None:
            mkw["allreduce_wire_dtype"] = wire
        return _run_managers(world, body, mkw)

    @pytest.mark.parametrize("wire", [None, jnp.bfloat16])
    def test_bitwise_vs_sync_path(self, wire):
        sync = self._train(2, False, 3, wire)
        shard = self._train(2, True, 3, wire)
        for r in range(2):
            for k in self.P0:
                np.testing.assert_array_equal(
                    sync[0]["params"][k], shard[r]["params"][k])

    def test_stripe_state_is_half_at_world2(self):
        shard = self._train(2, True, 2)
        full_bytes = sum(
            2 * v.nbytes for v in self.P0.values())  # adam mu+nu
        for r in range(2):
            got = shard[r]["state_bytes"]
            assert 0 < got < 0.62 * full_bytes, (got, full_bytes)
            assert shard[r]["metrics"]["update_count"] == 2
            assert shard[r]["metrics"]["update_ms_total"] > 0
            assert shard[r]["metrics"]["shard_state_bytes"] == got

    def test_plain_tree_in_shard_mode_uses_stripe_state(self):
        # Single-group fast paths hand apply() a plain averaged tree;
        # the world-1 stripe spelling must keep the SAME state store so
        # alternating paths never fork optimizer state.
        client = MagicMock()
        client.quorum.return_value = quorum_result(
            max_world_size=1, replica_world_size=1)
        client.should_commit.return_value = True
        m = make_manager(client, min_replica_size=1,
                         shard_update=True)
        try:
            with patch("torchft_tpu.manager.ManagerClient"):
                tx = optax.sgd(0.1)
                opt = FTOptimizer(m, tx, jit=False)
                h = _Holder(jax.tree_util.tree_map(jnp.asarray, self.P0))
                m.step()
                g = jax.tree_util.tree_map(jnp.asarray, self.P0)
                assert opt.apply(h, m.allreduce(g).result(timeout=30))
                # sgd: p - 0.1*g with g == p
                np.testing.assert_allclose(
                    np.asarray(h.params["b"]), 0.9 * self.P0["b"],
                    rtol=1e-6)
                assert opt._shard_state is not None
        finally:
            m.shutdown()


# ------------------------------------------------- striped heal

def _serve(state, n):
    servers = [CheckpointServer(lambda: state, bind_host="127.0.0.1")
               for _ in range(n)]
    for s in servers:
        s.allow_checkpoint(1)
    return servers


HEAL_STATE = {f"l{i}": np.random.default_rng(50 + i)
              .normal(size=16_384).astype(np.float32) for i in range(12)}


class TestStripedHeal:
    def test_three_donors_bitwise_and_all_used(self):
        servers = _serve(HEAL_STATE, 3)
        try:
            addrs = [s.address() for s in servers]
            stats = {}
            out = CheckpointServer.load_from_address(
                addrs[0], HEAL_STATE, device_put=False, stats=stats,
                donor_addrs=addrs, stripe_seed=3)
            for k, arr in HEAL_STATE.items():
                assert np.asarray(out[k]).tobytes() == arr.tobytes()
            assert stats["donors_used"] == 3.0, stats
            assert stats["attempts"] == 1.0
            assert stats["bytes_resumed"] == 0.0
        finally:
            for s in servers:
                s.shutdown()

    def test_dead_donor_reassigns_only_its_stripe(self):
        servers = _serve(HEAL_STATE, 2)
        try:
            addrs = [s.address() for s in servers]
            # A refused-dial donor in the set: its stripe reassigns to
            # the survivors; ONLY that stripe is re-fetched.
            dead = addrs[0].replace(
                f":{urllib.parse.urlparse(addrs[0]).port}", ":1")
            stats = {}
            out = CheckpointServer.load_from_address(
                addrs[0], HEAL_STATE, device_put=False, stats=stats,
                donor_addrs=[addrs[0], dead, addrs[1]], stripe_seed=0)
            for k, arr in HEAL_STATE.items():
                assert np.asarray(out[k]).tobytes() == arr.tobytes()
            assert stats["stripe_donor_deaths"] >= 1.0, stats
            assert 0 < stats["bytes_resumed"] < stats["payload_bytes"]
        finally:
            for s in servers:
                s.shutdown()

    def test_donor_killed_mid_stripe(self):
        """A donor that dies AFTER serving part of its stripe (chaos
        kill_after_bytes): committed leaves stay committed, only the
        dead donor's remaining stripe re-fetches, final state bitwise."""
        import urllib.parse
        import random as _random

        servers = _serve(HEAL_STATE, 3)
        try:
            addrs = [s.address() for s in servers]
            seed = 5
            # Replicate load_from_address's seed-shuffle to kill a NON-
            # manifest donor mid-stripe (the manifest donor dying is the
            # separate failover path, covered elsewhere).
            shuffled = list(dict.fromkeys(addrs))
            _random.Random(seed).shuffle(shuffled)
            victim = shuffled[1]
            netloc = urllib.parse.urlparse(victim).netloc
            payload = sum(a.nbytes for a in HEAL_STATE.values())
            sched = ChaosSchedule(seed=seed, endpoints={
                f"heal:{netloc}": EndpointChaos(
                    kill_after_bytes=payload // 8),
            })
            chaos.install(sched)
            try:
                stats = {}
                out = CheckpointServer.load_from_address(
                    addrs[0], HEAL_STATE, device_put=False, stats=stats,
                    donor_addrs=addrs, stripe_seed=seed,
                    stall_timeout_sec=10)
            finally:
                chaos.uninstall()
            for k, arr in HEAL_STATE.items():
                assert np.asarray(out[k]).tobytes() == arr.tobytes()
            assert stats["stripe_donor_deaths"] >= 1.0, stats
            assert stats["bytes_resumed"] < stats["payload_bytes"], stats
        finally:
            for s in servers:
                s.shutdown()

    def test_seed_shuffle_spreads_first_donor(self):
        """Concurrent healers must not all open their first stream
        against the same donor: across replica-id-derived seeds, the
        shuffled stripe[0] (the donor the manifest and first stripe ride)
        takes more than one value."""
        servers = _serve({"w": np.ones(64, np.float32)}, 3)
        try:
            addrs = [s.address() for s in servers]
            first = set()
            for i in range(8):
                seen = {}

                def capture(session, addr, *a, **kw):
                    seen["addr"] = addr
                    raise RuntimeError("probe only")

                with patch.object(CheckpointServer, "_run_heal_loop",
                                  side_effect=capture):
                    with pytest.raises(RuntimeError, match="probe"):
                        CheckpointServer.load_from_address(
                            addrs[0], {"w": np.ones(64, np.float32)},
                            device_put=False, donor_addrs=addrs,
                            stripe_seed=_stripe_seed(f"healer-{i}"))
                first.add(seen["addr"])
            assert len(first) > 1, first
        finally:
            for s in servers:
                s.shutdown()

    def test_wave_exception_not_blamed_on_survivors(self):
        """A zero-progress striped wave evicts the donor that actually
        died, then re-raises THAT donor's exception while ``addr`` still
        names a healthy survivor. The retry loop must re-stripe over the
        survivors — not evict/blame ``addr``, not burn a failover
        (regression: the handler used to attribute the wave's exception
        to the current manifest donor)."""
        servers = _serve(HEAL_STATE, 3)
        try:
            addrs = [s.address() for s in servers]
            real = CheckpointServer._fetch_striped.__func__
            calls = {"n": 0}

            def flaky(cls, session, stripe, *a, **kw):
                if calls["n"] == 0:
                    # First wave: donor stripe[1] "dies" with zero
                    # leaves landed — exactly what _fetch_striped does,
                    # including the already-handled tag on the raise.
                    calls["n"] += 1
                    dead = stripe.pop(1)
                    with session.lock:
                        session.stripe_deaths += 1
                    e = ConnectionRefusedError(f"[chaos] {dead} refused")
                    e._heal_striped_handled = True
                    raise e
                return real(cls, session, stripe, *a, **kw)

            resolver_calls = []

            def resolver(i):
                resolver_calls.append(i)
                return addrs[0]

            stats = {}
            with patch.object(CheckpointServer, "_fetch_striped",
                              classmethod(flaky)):
                out = CheckpointServer.load_from_address(
                    addrs[0], HEAL_STATE, device_put=False, stats=stats,
                    donor_addrs=addrs, stripe_seed=0, donors=resolver)
            for k, arr in HEAL_STATE.items():
                assert np.asarray(out[k]).tobytes() == arr.tobytes()
            # ONE death, counted once; the survivors kept striping — no
            # failover burned, the resolver never consulted.
            assert stats["stripe_donor_deaths"] == 1.0, stats
            assert stats["donor_failovers"] == 0.0, stats
            assert not resolver_calls
        finally:
            for s in servers:
                s.shutdown()

    def test_single_donor_set_falls_back_to_plain_fetch(self):
        servers = _serve(HEAL_STATE, 1)
        try:
            stats = {}
            out = CheckpointServer.load_from_address(
                servers[0].address(), HEAL_STATE, device_put=False,
                stats=stats, donor_addrs=[servers[0].address()],
                stripe_seed=1)
            for k, arr in HEAL_STATE.items():
                assert np.asarray(out[k]).tobytes() == arr.tobytes()
            assert stats["donors_used"] == 1.0
        finally:
            for s in servers:
                s.shutdown()

    def test_serve_window_shares_one_plan(self):
        """Donor-side fix: concurrent requests of one serve window share
        ONE cached PytreePlan (and its once-computed digests) —
        lock_streaming mode included, where each GET used to re-plan
        (and re-digest) the live tree. Manifests 404 in lock_streaming
        mode, so the cache is probed with concurrent full GETs."""
        state = {"w": np.arange(4096, dtype=np.float32)}
        calls = []
        import torchft_tpu.checkpointing as cpt
        real = cpt.plan_pytree

        def counting(tree):
            calls.append(1)
            return real(tree)

        server = CheckpointServer(lambda: state, lock_streaming=True,
                                  bind_host="127.0.0.1")
        try:
            with patch.object(cpt, "plan_pytree", side_effect=counting):
                server.allow_checkpoint(1)
                url = server.address()
                errs = []

                def get():
                    try:
                        with urllib.request.urlopen(url, timeout=10) as r:
                            r.read()
                    except Exception as e:  # noqa: BLE001
                        errs.append(e)

                ts = [threading.Thread(target=get) for _ in range(4)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(timeout=30)
                assert not errs, errs
                assert len(calls) == 1, f"planned {len(calls)} times"
        finally:
            server.shutdown()


# -------------------------------------------- sharded checkpoints

class TestShardedCheckpoint:
    STATE = {"w": np.arange(60_000, dtype=np.float32).reshape(60, 1000),
             "b": np.ones(7, np.float64), "step": 3}

    def _target(self):
        return {"w": np.zeros((60, 1000), np.float32),
                "b": np.zeros(7), "step": 0}

    def test_roundtrip_and_verify(self, tmp_path):
        from torchft_tpu import checkpoint_io as cio

        p = str(tmp_path / "ckpt_5")
        cio.save_sharded(p, self.STATE, {"step": 5,
                                         "batches_committed": 5},
                         shards=3)
        names = sorted(os.listdir(tmp_path))
        assert names == ["ckpt_5", "ckpt_5.shard0", "ckpt_5.shard1",
                         "ckpt_5.shard2"]
        head = cio.verify(p)
        assert head["format"] == cio.SET_FORMAT
        assert head["shard_count"] == 3
        assert cio.read_meta(p)["step"] == 5
        user, mgr = cio.load(p, self._target(), device_put=False)
        np.testing.assert_array_equal(user["w"], self.STATE["w"])
        np.testing.assert_array_equal(user["b"], self.STATE["b"])
        assert user["step"] == 3 and mgr["step"] == 5

    def test_one_shard_set_is_valid(self, tmp_path):
        from torchft_tpu import checkpoint_io as cio

        p = str(tmp_path / "ckpt_1")
        cio.save_sharded(p, self.STATE, {"step": 1,
                                         "batches_committed": 1},
                         shards=1)
        cio.verify(p)
        user, _ = cio.load(p, self._target(), device_put=False)
        np.testing.assert_array_equal(user["w"], self.STATE["w"])

    def test_missing_shard_condemns_set(self, tmp_path):
        from torchft_tpu import checkpoint_io as cio

        p = str(tmp_path / "ckpt_9")
        cio.save_sharded(p, self.STATE, {"step": 9,
                                         "batches_committed": 9},
                         shards=2)
        os.unlink(p + ".shard0")
        with pytest.raises(cio.CheckpointCorruptError,
                           match="missing shard"):
            cio.verify(p)
        assert cio.recover(str(tmp_path)) is None

    def test_corrupt_shard_falls_back_to_older_complete(self, tmp_path):
        from torchft_tpu import checkpoint_io as cio

        old = str(tmp_path / "ckpt_4")
        cio.save(old, self.STATE, {"step": 4, "batches_committed": 4})
        p = str(tmp_path / "ckpt_5")
        cio.save_sharded(p, self.STATE, {"step": 5,
                                         "batches_committed": 5},
                         shards=2)
        # Flip one byte deep in shard1's payload.
        with open(p + ".shard1", "r+b") as f:
            f.seek(-20, os.SEEK_END)
            b = f.read(1)
            f.seek(-20, os.SEEK_END)
            f.write(bytes([b[0] ^ 0xFF]))
        stats = {}
        got = cio.recover(str(tmp_path), stats=stats)
        assert got is not None and got.endswith("ckpt_4")
        assert stats["ckpt_recover_fallbacks"] >= 1
        # The condemned set's members went aside with its head.
        leftover = [n for n in os.listdir(tmp_path)
                    if n.startswith("ckpt_5")
                    and not n.endswith(".corrupt")]
        assert not leftover, leftover
        # Monolithic v2 still loads after the fallback.
        user, _ = cio.load(got, self._target(), device_put=False)
        np.testing.assert_array_equal(user["w"], self.STATE["w"])

    def test_stale_generation_shard_rejected(self, tmp_path):
        """A shard left over from an OLDER save under the same name must
        not satisfy a newer head: set_id binds shards to their save."""
        from torchft_tpu import checkpoint_io as cio

        p = str(tmp_path / "ckpt_7")
        cio.save_sharded(p, self.STATE, {"step": 7,
                                         "batches_committed": 7},
                         shards=2)
        old_shard = (tmp_path / "ckpt_7.shard0").read_bytes()
        cio.save_sharded(p, self.STATE, {"step": 7,
                                         "batches_committed": 7},
                         shards=2)
        (tmp_path / "ckpt_7.shard0").write_bytes(old_shard)
        with pytest.raises(cio.CheckpointCorruptError,
                           match="set_id mismatch"):
            cio.verify(p)

    def test_async_checkpointer_shards_and_prunes(self, tmp_path):
        from torchft_tpu import checkpoint_io as cio
        from torchft_tpu.checkpoint_io import AsyncCheckpointer

        w = AsyncCheckpointer(keep=1, shards=2)
        try:
            for step in (1, 2):
                w.save_async(str(tmp_path / f"ckpt_{step}"), self.STATE,
                             {"step": step, "batches_committed": step})
                w.wait()
        finally:
            w.shutdown()
        names = sorted(os.listdir(tmp_path))
        # keep=1 pruned step 1's head AND its stripe files.
        assert names == ["ckpt_2", "ckpt_2.shard0", "ckpt_2.shard1"], \
            names
        got = cio.recover(str(tmp_path))
        assert got is not None and got.endswith("ckpt_2")
        user, _ = cio.load(got, self._target(), device_put=False)
        np.testing.assert_array_equal(user["w"], self.STATE["w"])
