"""Serving-tier tests (docs/design/serving.md): the delta-publication
protocol (head / manifest / ranged bytes, generation eviction,
long-poll), the crc-verified atomic swap (torn-read guarantee under
``TORCHFT_CHAOS`` net faults, publisher restart, relay death), delta
minimality (byte counters: a subscriber at generation G reaching G+1
fetches only changed-digest leaves), the relay fan-out tree, staleness
bounds, Manager.publish commit coupling, and ranged-fetch connection
reuse. The seeded subscriber-churn soak rides ``scripts/test.sh serve``
nightly (markers ``serve`` + ``slow`` + ``nightly``).

The CDN-scale half (marker ``relay``, ``scripts/test.sh relay``):
the quantized delta wire (``tft-publish-delta-1`` doc/body routes,
1/4-byte minimality, bitwise reconstruction, per-leaf crc fallback,
verbatim relay adoption), the lock-striped ``_RelayTable`` battery,
registration beats + head-fetch steering (dead-hint cooldown, TTL
expiry, relay-death re-parenting), and the steered-delta churn soak
(``relay`` + ``slow`` + ``nightly``).

No native library needed: the tier is pure HTTP + numpy.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import Future
from unittest.mock import MagicMock

import numpy as np
import pytest

from test_manager import make_manager, quorum_result
from torchft_tpu import chaos as chaos_mod
from torchft_tpu.chaos import ChaosSchedule, EndpointChaos
from torchft_tpu.checkpointing import CheckpointServer, _ConnectionPool
from torchft_tpu.retry import RetryError, RetryPolicy
from torchft_tpu.serialization import manifest_delta
from torchft_tpu.serving import (
    DELTA_FORMAT,
    HEAD_FORMAT,
    PublicationServer,
    StaleWeightsError,
    WeightPublisher,
    WeightRelay,
    WeightSubscriber,
    _DeltaSet,
    _RelayTable,
    _serve_endpoint,
)

pytestmark = pytest.mark.serve

# Varied leaf sizes so delta byte accounting is unambiguous.
_SIZES = {"emb": 4000, "w1": 2500, "b1": 100, "w2": 1500, "b2": 50,
          "head": 800}


def make_state(fill=None, seed=0):
    rng = np.random.default_rng(seed)
    out = {}
    for k, n in _SIZES.items():
        out[k] = (np.full(n, float(fill), np.float32) if fill is not None
                  else rng.normal(size=n).astype(np.float32))
    out["step"] = 0
    return out


def template():
    return {k: np.zeros(n, np.float32) for k, n in _SIZES.items()} \
        | {"step": 0}


def leaf_bytes(*names):
    return sum(_SIZES[n] * 4 for n in names)


def assert_bitwise(a, b):
    for k in _SIZES:
        assert a[k].tobytes() == b[k].tobytes(), f"leaf {k} differs"


def fast_policy():
    return RetryPolicy(max_attempts=4, base_delay_ms=5.0, jitter=0.0)


@pytest.fixture
def rig():
    pub = WeightPublisher(keep_generations=2)
    srv = PublicationServer(pub, bind_host="127.0.0.1")
    subs = []

    def make_sub(parents=None, **kw):
        kw.setdefault("retry_policy", fast_policy())
        kw.setdefault("stall_timeout_sec", 10.0)
        s = WeightSubscriber(parents or srv.address(), template(), **kw)
        subs.append(s)
        return s

    yield pub, srv, make_sub
    for s in subs:
        s.stop()
    srv.shutdown()


class TestPublicationProtocol:
    def test_head_404_before_first_publish(self, rig):
        pub, srv, _ = rig
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.address() + "/head", timeout=10)
        assert ei.value.code == 404

    def test_head_manifest_and_ranged_data(self, rig):
        pub, srv, _ = rig
        state = make_state(seed=3)
        gen = pub.publish(state, step=7)
        with urllib.request.urlopen(srv.address() + "/head",
                                    timeout=10) as r:
            head = json.loads(r.read())
        assert head["format"] == HEAD_FORMAT
        assert head["generation"] == gen
        assert head["step"] == 7
        assert head["boot"]
        with urllib.request.urlopen(
                f"{srv.address()}/{gen}/manifest", timeout=10) as r:
            mf = json.loads(r.read())
        arrs = [e for e in mf["leaves"] if e["kind"] == "array"]
        assert len(arrs) == len(_SIZES)
        assert all("crc32" in e for e in arrs)
        assert mf["generation"] == gen and mf["step"] == 7
        # ranged read of one leaf's exact bytes (leaves flatten in
        # sorted-key order — look "emb" up by name)
        e = next(e for e in arrs if e["key"] == "emb")
        a = mf["preamble_len"] + e["offset"]
        req = urllib.request.Request(
            f"{srv.address()}/{gen}",
            headers={"Range": f"bytes={a}-{a + e['nbytes'] - 1}"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 206
            body = r.read()
        assert body == state["emb"].tobytes()
        # unsatisfiable range
        req = urllib.request.Request(
            f"{srv.address()}/{gen}",
            headers={"Range": f"bytes={mf['total_len'] + 5}-"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 416
        assert ei.value.headers["Content-Range"] == \
            f"bytes */{mf['total_len']}"

    def test_generation_eviction(self, rig):
        pub, srv, _ = rig
        for g in range(1, 4):
            pub.publish(make_state(fill=g), step=g)
        # keep_generations=2: gen 1 is gone, 2 and 3 fetchable
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{srv.address()}/1/manifest",
                                   timeout=10)
        assert ei.value.code == 404
        for g in (2, 3):
            with urllib.request.urlopen(f"{srv.address()}/{g}/manifest",
                                        timeout=10) as r:
                assert json.loads(r.read())["generation"] == g

    def test_long_poll_returns_on_publish(self, rig):
        pub, srv, make_sub = rig
        pub.publish(make_state(fill=1), step=1)
        sub = make_sub()
        assert sub.sync() is True
        threading.Timer(
            0.3, lambda: pub.publish(make_state(fill=2), step=2)).start()
        t0 = time.monotonic()
        assert sub.sync(wait_s=5.0) is True
        elapsed = time.monotonic() - t0
        assert elapsed < 4.0, "long-poll should return on publish, not " \
                              f"timeout (took {elapsed:.1f}s)"
        assert sub.generation() == 2

    def test_auth_token_gate(self):
        pub = WeightPublisher()
        srv = PublicationServer(pub, bind_host="127.0.0.1",
                                auth_token="sekrit")
        try:
            pub.publish(make_state(fill=1), step=1)
            bad = WeightSubscriber(srv.address(), template(),
                                   retry_policy=fast_policy())
            with pytest.raises(urllib.error.HTTPError) as ei:
                bad.sync()
            assert ei.value.code == 401
            good = WeightSubscriber(srv.address(), template(),
                                    auth_token="sekrit",
                                    retry_policy=fast_policy())
            assert good.sync() is True
            bad.stop()
            good.stop()
        finally:
            srv.shutdown()

    def test_manifest_delta_unit(self):
        pub = WeightPublisher()
        s1 = make_state(seed=1)
        pub.publish(s1, step=1)
        mf1 = pub._head.manifest
        s2 = dict(s1)
        s2["b1"] = s1["b1"] + 1
        pub.publish(s2, step=2)
        mf2 = pub._head.manifest
        d = manifest_delta(mf1, mf2)
        assert d["changed_bytes"] == leaf_bytes("b1")
        assert len(d["changed"]) == 1
        assert d["leaves"] == len(_SIZES)
        cold = manifest_delta(None, mf2)
        assert cold["changed_bytes"] == d["total_bytes"]


class TestDeltaFetch:
    def test_first_sync_is_full_then_delta_minimal(self, rig):
        pub, srv, make_sub = rig
        s1 = make_state(seed=5)
        pub.publish(s1, step=1)
        sub = make_sub()
        assert sub.sync() is True
        m = sub.metrics()
        # first sync fetches every leaf's body bytes
        assert m["serve_delta_bytes_last"] == leaf_bytes(*_SIZES)
        assert m["serve_leaves_carried_last"] == 0
        assert_bitwise(sub.weights(), s1)
        # small-touch update: two leaves change
        s2 = dict(s1)
        s2["b2"] = s1["b2"] * 2 + 1
        s2["head"] = s1["head"] + 0.5
        pub.publish(s2, step=2)
        assert sub.sync() is True
        m = sub.metrics()
        assert m["serve_delta_bytes_last"] == leaf_bytes("b2", "head")
        assert m["serve_leaves_fetched_last"] == 2
        assert m["serve_leaves_carried_last"] == len(_SIZES) - 2
        assert_bitwise(sub.weights(), s2)
        # publisher-side delta accounting agrees
        pm = pub.metrics()
        assert pm["publish_delta_bytes_last"] == leaf_bytes("b2", "head")
        assert pm["publish_changed_leaves_last"] == 2

    def test_identical_republish_costs_zero_bytes(self, rig):
        pub, srv, make_sub = rig
        s1 = make_state(seed=6)
        pub.publish(s1, step=1)
        sub = make_sub()
        sub.sync()
        pub.publish(dict(s1), step=2)  # nothing changed
        assert sub.sync() is True
        m = sub.metrics()
        assert m["serve_delta_bytes_last"] == 0
        assert m["serve_leaves_carried_last"] == len(_SIZES)
        assert sub.generation() == 2

    def test_skip_ahead_generations(self, rig):
        """A slow subscriber jumping G -> G+2 still fetches one delta
        (vs the newest), not the intermediate history."""
        pub, srv, make_sub = rig
        s1 = make_state(seed=7)
        pub.publish(s1, step=1)
        sub = make_sub()
        sub.sync()
        s2 = dict(s1)
        s2["b1"] = s1["b1"] + 1
        pub.publish(s2, step=2)
        s3 = dict(s2)
        s3["b1"] = s2["b1"] + 1
        pub.publish(s3, step=3)
        assert sub.sync() is True
        assert sub.generation() == 3
        assert sub.metrics()["serve_delta_bytes_last"] == leaf_bytes("b1")
        assert_bitwise(sub.weights(), s3)

    def test_device_put_subscriber(self, rig):
        import jax
        import jax.numpy as jnp

        pub, srv, _ = rig
        s1 = make_state(seed=8)
        pub.publish(s1, step=1)
        tmpl = {k: jnp.zeros(n, jnp.float32) for k, n in _SIZES.items()} \
            | {"step": 0}
        sub = WeightSubscriber(srv.address(), tmpl, device_put=True,
                               retry_policy=fast_policy())
        try:
            assert sub.sync() is True
            w = sub.weights()
            assert isinstance(w["emb"], jax.Array)
            assert np.asarray(w["emb"]).tobytes() == s1["emb"].tobytes()
        finally:
            sub.stop()


class TestTornReadGuarantee:
    """The acceptance invariant: under net chaos, publisher restart, and
    relay death mid-transfer, a subscriber NEVER observes a torn or
    uncommitted weight set — every visible tree is bitwise one of the
    published generations."""

    def _assert_uniform(self, tree, expected_gens):
        vals = {k: tree[k][0] for k in _SIZES}
        first = next(iter(vals.values()))
        assert all(v == first for v in vals.values()), \
            f"TORN TREE: mixed generation fills {vals}"
        for k in _SIZES:
            assert np.all(tree[k] == tree[k][0]), f"torn leaf {k}"
        assert int(first) in expected_gens, \
            f"unpublished fill {first} observed"

    def test_chaos_net_faults_never_tear(self, rig):
        pub, srv, make_sub = rig
        sched = ChaosSchedule(seed=1234, endpoints={
            "serve": EndpointChaos(reset_rate=0.10, short_rate=0.15),
        })
        chaos_mod.install(sched)
        try:
            sub = make_sub()
            published = set()
            for g in range(1, 6):
                pub.publish(make_state(fill=g), step=g)
                published.add(g)
                deadline = time.monotonic() + 60
                while sub.generation() < g:
                    try:
                        sub.sync()
                    except (RetryError, urllib.error.HTTPError,
                            ConnectionError, ValueError):
                        pass  # chaos round; held weights must stay sane
                    self._assert_uniform(sub.weights(), published) \
                        if sub.generation() else None
                    assert time.monotonic() < deadline, \
                        "sync never converged under chaos"
                self._assert_uniform(sub.weights(), {g})
            assert sched.fault_count() > 0, "chaos never fired — rig bug"
            assert_bitwise(sub.weights(), make_state(fill=5))
        finally:
            chaos_mod.uninstall()

    def test_parent_kill_mid_transfer_then_revive(self, rig):
        pub, srv, make_sub = rig
        s1 = make_state(fill=1)
        pub.publish(s1, step=1)
        sub = make_sub()
        sub.sync()
        ep = _serve_endpoint(srv.address())
        sched = ChaosSchedule(seed=7)
        chaos_mod.install(sched)
        try:
            sched.kill_endpoint(ep)
            pub.publish(make_state(fill=2), step=2)
            with pytest.raises((RetryError, ConnectionError)):
                sub.sync()
            # held weights unchanged and whole
            assert_bitwise(sub.weights(), s1)
            sched.revive_endpoint(ep)
            assert sub.sync() is True
            assert_bitwise(sub.weights(), make_state(fill=2))
        finally:
            chaos_mod.uninstall()

    def test_publisher_restart_new_boot(self):
        """A restarted publisher (fresh boot nonce, generation counter
        reset) must neither wedge nor tear the subscriber: the boot
        change forces a resync, digests carry unchanged leaves over."""
        pub1 = WeightPublisher()
        srv1 = PublicationServer(pub1, bind_host="127.0.0.1")
        port = int(srv1.address().rsplit(":", 1)[1].split("/")[0])
        s1 = make_state(seed=9)
        pub1.publish(s1, step=10)
        pub1.publish(s1, step=11)  # gen 2, same bytes
        sub = WeightSubscriber(srv1.address(), template(),
                               retry_policy=fast_policy())
        try:
            sub.sync()
            assert sub.generation() == 2
            srv1.shutdown()
            # "restart": fresh publisher process on the same port — new
            # boot, generation counter back at 1, one leaf changed.
            pub2 = WeightPublisher()
            s2 = dict(s1)
            s2["w2"] = s1["w2"] + 3
            srv2 = PublicationServer(pub2, bind_host="127.0.0.1",
                                     port=port)
            try:
                pub2.publish(s2, step=12)
                assert sub.sync() is True
                assert sub.generation() == 1  # new life's counter
                assert sub.step() == 12
                assert_bitwise(sub.weights(), s2)
                # digest carryover made the restart cheap: only the
                # changed leaf crossed the wire
                m = sub.metrics()
                assert m["serve_delta_bytes_last"] == leaf_bytes("w2")
            finally:
                srv2.shutdown()
        finally:
            sub.stop()


class TestBootTransitions:
    def test_no_flip_flop_between_stale_relay_and_restarted_root(self):
        """A wedged relay still serving the PREVIOUS publisher life next
        to a restarted root must not make the subscriber oscillate
        between lives: once a swap leaves boot A for boot B, boot A can
        never look 'fresher' again."""
        pub1 = WeightPublisher()
        srv1 = PublicationServer(pub1, bind_host="127.0.0.1")
        s_old = make_state(fill=1)
        pub1.publish(s_old, step=9)
        pub1.publish(s_old, step=9)  # gen 2 of boot A
        relay = WeightRelay(srv1.address(), template(),
                            bind_host="127.0.0.1",
                            retry_policy=fast_policy(), name="relayOld")
        relay.sync()  # holds boot A gen 2; its uplink now "wedges"
        # root restarts: new boot, counter back at 1, different state
        srv1.shutdown()
        pub2 = WeightPublisher()
        s_new = make_state(fill=2)
        srv2 = PublicationServer(pub2, bind_host="127.0.0.1")
        pub2.publish(s_new, step=3)
        sub = WeightSubscriber([relay.address(), srv2.address()],
                               template(), retry_policy=fast_policy())
        try:
            # converge onto the live life (may take one probe round)
            deadline = time.monotonic() + 20
            while True:
                sub.sync()
                if sub.weights()["emb"][0] == 2.0:
                    break
                assert time.monotonic() < deadline, "never left boot A"
            # ...and STAY there: the stale relay's old life must never
            # win again, no matter how many polls
            sub._last_probe = 0.0  # force the next probe window open
            for _ in range(6):
                assert sub.sync() is False
                assert sub.weights()["emb"][0] == 2.0
                assert_bitwise(sub.weights(), s_new)
        finally:
            sub.stop()
            relay.stop()
            srv2.shutdown()

    def test_cold_start_step_regression_resets_staleness(self):
        """A publisher cold-started from an old checkpoint legitimately
        REGRESSES steps (100 -> 60, new boot). Subscribers holding the
        newest generation in existence must not go dark on a staleness
        gauge still pinned at the dead life's step 100."""
        pub1 = WeightPublisher()
        srv1 = PublicationServer(pub1, bind_host="127.0.0.1")
        port = int(srv1.address().rsplit(":", 1)[1].split("/")[0])
        pub1.publish(make_state(fill=1), step=100)
        sub = WeightSubscriber(srv1.address(), template(),
                               retry_policy=fast_policy(),
                               max_lag_steps=5)
        try:
            sub.sync()
            assert sub.step() == 100
            srv1.shutdown()
            pub2 = WeightPublisher()
            srv2 = PublicationServer(pub2, bind_host="127.0.0.1",
                                     port=port)
            try:
                pub2.publish(make_state(fill=2), step=60)
                assert sub.sync() is True
                assert sub.step() == 60
                assert sub.lag_steps() == 0
                # the whole point: newest weights in existence stay
                # servable despite the apparent 40-step "lag"
                assert sub.weights()["emb"][0] == 2.0
            finally:
                srv2.shutdown()
        finally:
            sub.stop()


class TestRelayTree:
    def test_relay_serves_downstream_bitwise(self, rig):
        pub, srv, make_sub = rig
        s1 = make_state(seed=11)
        pub.publish(s1, step=1)
        relay = WeightRelay(srv.address(), template(),
                            bind_host="127.0.0.1",
                            retry_policy=fast_policy(), name="relayA")
        try:
            assert relay.sync() is True
            down = make_sub(parents=relay.address())
            assert down.sync() is True
            assert down.generation() == 1
            assert_bitwise(down.weights(), s1)
            # generation identity propagates: delta against the relay
            s2 = dict(s1)
            s2["b1"] = s1["b1"] - 1
            pub.publish(s2, step=2)
            relay.sync()
            down.sync()
            # only b1 moved; the relay's delta-mode publisher may serve
            # it as an exact-gated quantized wire (the -1 shift
            # reproduces bitwise), so the byte count is AT MOST the
            # changed leaf's f32 size — never the whole tree
            dm = down.metrics()
            assert 0 < dm["serve_delta_bytes_last"] <= leaf_bytes("b1")
            assert_bitwise(down.weights(), s2)
            rm = relay.metrics()
            assert rm["relay_publish_generations"] == 2
            assert rm["relay_serve_bytes_sent"] > 0
        finally:
            relay.stop()

    def test_stale_but_alive_relay_does_not_pin_subscriber(self, rig):
        """A relay whose own uplink wedged (alive, serving an old head)
        must not pin its subscribers: the already-current probe finds
        the fresher sibling parent, re-targets it, and the advertised
        head step still feeds the staleness gauge."""
        pub, srv, make_sub = rig
        s1 = make_state(seed=21)
        pub.publish(s1, step=1)
        relay = WeightRelay(srv.address(), template(),
                            bind_host="127.0.0.1",
                            retry_policy=fast_policy(), name="relayS")
        try:
            relay.sync()  # holds gen 1; never polls again (wedged)
            down = make_sub(parents=[relay.address(), srv.address()])
            down.sync()
            assert down.generation() == 1
            s2 = dict(s1)
            s2["w1"] = s1["w1"] + 1
            pub.publish(s2, step=2)  # relay never learns of gen 2
            assert down.sync() is True
            assert down.generation() == 2
            assert_bitwise(down.weights(), s2)
            assert down.metrics()["serve_delta_bytes_last"] == \
                leaf_bytes("w1")
        finally:
            relay.stop()

    def test_relay_death_fails_over_to_root(self, rig):
        """Relay dies mid-life: its subscriber rotates to the root
        publisher, resuming from committed (digest-matching) leaves —
        the delta stays a delta across the failover."""
        pub, srv, make_sub = rig
        s1 = make_state(seed=12)
        pub.publish(s1, step=1)
        relay = WeightRelay(srv.address(), template(),
                            bind_host="127.0.0.1",
                            retry_policy=fast_policy(), name="relayB")
        relay.sync()
        down = make_sub(parents=[relay.address(), srv.address()])
        down.sync()
        assert_bitwise(down.weights(), s1)
        relay.stop()  # relay process "dies"
        s2 = dict(s1)
        s2["head"] = s1["head"] * 0.5
        pub.publish(s2, step=2)
        assert down.sync() is True
        m = down.metrics()
        assert m["serve_parent_failovers"] >= 1
        assert m["serve_delta_bytes_last"] == leaf_bytes("head")
        assert m["serve_leaves_carried_last"] == len(_SIZES) - 1
        assert_bitwise(down.weights(), s2)


class TestStaleness:
    def test_max_lag_steps_bound(self, rig):
        pub, srv, make_sub = rig
        pub.publish(make_state(fill=1), step=10)
        # A "parent" that advertises step 15 but serves no data: the
        # subscriber learns how far behind it is, cannot close the gap.
        class HeadOnly(WeightPublisher):
            def handle_request(self, handler, send_timeout_sec=120.0):
                if handler.path.split("?")[0].rstrip("/") in (
                        "/publish", "/publish/head"):
                    self._send_json(handler, {
                        "format": HEAD_FORMAT, "generation": 99,
                        "step": 15, "boot": "elsewhere",
                        "total_len": 0, "manifest": "/publish/99/manifest",
                        "data": "/publish/99"}, send_timeout_sec)
                else:
                    handler.send_error(404, "no data here")

        fake_srv = PublicationServer(HeadOnly(), bind_host="127.0.0.1")
        try:
            sub = make_sub(parents=[srv.address()], max_lag_steps=3)
            sub.sync()
            assert sub.weights() is not None  # lag 0: fine
            # now the fleet's head moves to step 15 where we can't
            # follow (no data behind it): sync either rotates back to
            # the real parent and reports nothing new, or exhausts its
            # budget — either way the advertised step was LEARNED
            sub._parents.append(fake_srv.address())
            sub._parent_idx = 1
            try:
                sub.sync()
            except RetryError:
                pass
            assert sub.lag_steps() == 5
            with pytest.raises(StaleWeightsError):
                sub.weights()
            # a looser bound serves stale-but-bounded weights
            sub._max_lag_steps = 10
            assert sub.weights()["emb"][0] == 1.0
        finally:
            fake_srv.shutdown()

    def test_no_generation_yet_raises(self, rig):
        _, _, make_sub = rig
        sub = make_sub()
        with pytest.raises(StaleWeightsError):
            sub.weights()

    def test_background_thread_and_wait_generation(self, rig):
        pub, srv, make_sub = rig
        sub = make_sub(poll_interval_s=0.05)
        sub.start()
        pub.publish(make_state(fill=4), step=4)
        assert sub.wait_generation(1, timeout=20)
        assert_bitwise(sub.weights(), make_state(fill=4))
        pub.publish(make_state(fill=5), step=5)
        assert sub.wait_generation(2, timeout=20)
        sub.stop()


class TestConnectionReuse:
    def test_subscriber_reuses_connections(self, rig):
        pub, srv, make_sub = rig
        pub.publish(make_state(fill=1), step=1)
        sub = make_sub()
        sub.sync()
        pub.publish(make_state(fill=2), step=2)
        sub.sync()
        # 2 syncs = >= 4 requests (head+manifest+data each) over one
        # parent: everything after the first dial rides the kept-alive
        # connection.
        assert sub.metrics()["serve_redials_avoided"] >= 3

    def test_heal_fetch_reuses_connections(self):
        state = make_state(seed=13)
        server = CheckpointServer(lambda: state, bind_host="127.0.0.1")
        try:
            server.allow_checkpoint(1)
            stats = {}
            got = CheckpointServer.load_from_address(
                server.address(), template(), device_put=False,
                stats=stats)
            assert_bitwise(got, state)
            # manifest + body ride one connection: the second request
            # avoided a redial
            assert stats["redials_avoided"] >= 1
        finally:
            server.shutdown()

    def test_pool_survives_server_side_close(self):
        """A pooled connection the server idle-closed must transparently
        re-dial, not fail the request."""
        state = make_state(seed=14)
        pub = WeightPublisher()
        srv = PublicationServer(pub, bind_host="127.0.0.1",
                                send_timeout_sec=0.4)
        try:
            pub.publish(state, step=1)
            pool = _ConnectionPool()
            for i in range(2):
                resp = pool.request(f"{srv.address()}/head", 10.0, None)
                with resp:
                    assert json.loads(resp.read())["generation"] == 1
                time.sleep(0.8)  # server idle-closes the kept conn
            assert pool.redials >= 1
        finally:
            pool.close()
            srv.shutdown()


class TestManagerPublish:
    def _happy(self, state):
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = True
        return make_manager(client, state_dict=lambda: state)

    def test_publish_and_subscribe_end_to_end(self):
        state = make_state(seed=15)
        m = self._happy(state)
        pub = WeightPublisher()
        sub = None
        try:
            m.step()
            assert m.should_commit()
            gen = m.publish(pub)
            assert gen == 1
            # served through the manager's own CheckpointServer — and
            # NOT step-gated: a closed heal window (commit in progress)
            # must not block publication fetches.
            m._ckpt_server.disallow_checkpoint()
            sub = WeightSubscriber(m.publish_address(), template(),
                                   retry_policy=fast_policy())
            assert sub.sync() is True
            assert_bitwise(sub.weights(), state)
            assert sub.step() == 1
            mx = m.metrics()
            assert mx["publish_count"] == 1
            assert mx["publish_last_generation"] == 1
            assert mx["publish_generations"] == 1
            assert "publish" in [e["event"] for e in m.history()]
        finally:
            if sub is not None:
                sub.stop()
            m.shutdown()

    def test_refuses_errored_aborted_healing_deferred(self):
        state = make_state(seed=16)
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = False  # vote aborts
        m = make_manager(client, state_dict=lambda: state)
        pub = WeightPublisher()
        try:
            m.step()
            m.report_error(RuntimeError("boom"))
            assert m.publish(pub) is None          # errored
            assert not m.should_commit()
            assert m.publish(pub) is None          # aborted
            with m._metrics_lock:
                m._healing = True
            assert m.publish(pub) is None          # mid-heal
            with m._metrics_lock:
                m._healing = False
            m._should_step = True
            m._errored = None
            fut = Future()
            m.stage_deferred(fut)
            assert m.publish(pub) is None          # deferred in flight
            fut.set_result(None)
            m.drain_deferred()
            mx = m.metrics()
            assert mx["publish_skipped"] == 4
            assert mx["publish_count"] == 0
            assert pub.head() is None  # nothing ever served
            skips = [e for e in m.history()
                     if e["event"] == "publish_skip"]
            assert len(skips) == 4
        finally:
            m.shutdown()


@pytest.mark.slow
@pytest.mark.nightly
class TestSubscriberChurnSoak:
    """Seeded churn soak: continuous publishing through a 2-relay tree
    while subscribers die/restart, one relay is killed mid-publish, and
    the serve channel injects resets/shorts — every visible tree must
    stay one of the published generations bitwise, and the fleet must
    converge on the final generation once the churn stops."""

    def test_churn_soak(self):
        sched = ChaosSchedule(seed=99, endpoints={
            "serve": EndpointChaos(reset_rate=0.04, short_rate=0.06),
        })
        chaos_mod.install(sched)
        pub = WeightPublisher(keep_generations=3)
        srv = PublicationServer(pub, bind_host="127.0.0.1")
        relays = [WeightRelay(srv.address(), template(),
                              bind_host="127.0.0.1",
                              retry_policy=fast_policy(),
                              poll_interval_s=0.05,
                              name=f"relay{i}").start()
                  for i in range(2)]
        subs = [WeightSubscriber(
                    [relays[i % 2].address(), srv.address()], template(),
                    retry_policy=fast_policy(), poll_interval_s=0.05,
                    name=f"sub{i}").start()
                for i in range(4)]
        published = set()
        torn: list = []

        def check(sub):
            try:
                tree = sub.weights()
            except StaleWeightsError:
                return
            vals = {k: tree[k][0] for k in _SIZES}
            first = next(iter(vals.values()))
            if not all(v == first for v in vals.values()) \
                    or int(first) not in published:
                torn.append((sub._name, vals))

        try:
            final_gen = 14
            for g in range(1, final_gen + 1):
                pub.publish(make_state(fill=g), step=g)
                published.add(g)
                for s in subs:
                    check(s)
                if g == 5:
                    # kill relay 0 mid-publish sequence: its subscribers
                    # must fail over to the root
                    sched.kill_endpoint(_serve_endpoint(
                        relays[0].address()))
                if g == 8:
                    # subscriber churn: one dies, a cold one joins
                    subs[0].stop()
                    subs[0] = WeightSubscriber(
                        [relays[1].address(), srv.address()], template(),
                        retry_policy=fast_policy(), poll_interval_s=0.05,
                        name="sub0b").start()
                if g == 10:
                    sched.revive_endpoint(_serve_endpoint(
                        relays[0].address()))
                time.sleep(0.25)
            # churn over: everyone must converge on the final state
            deadline = time.monotonic() + 90
            expected = make_state(fill=final_gen)
            for s in subs:
                while True:
                    check(s)
                    if s.generation() == final_gen:
                        break
                    assert time.monotonic() < deadline, \
                        f"{s._name} never converged " \
                        f"(at gen {s.generation()})"
                    time.sleep(0.1)
                assert_bitwise(s.weights(), expected)
            assert not torn, f"torn/unpublished trees observed: {torn}"
            assert sched.fault_count() > 0
        finally:
            chaos_mod.uninstall()
            for s in subs:
                s.stop()
            for r in relays:
                r.stop()
            srv.shutdown()


@pytest.mark.relay
class TestRelayTable:
    """The lock-striped beat table behind steering — unit battery."""

    def test_beat_rows_ttl_prune_and_age(self):
        t = _RelayTable(ttl_s=0.25)
        t.beat("r1", {"addr": "http://a/publish", "boot": "b",
                      "gen": 3, "children": 1})
        t.beat("r2", {"addr": "http://b/publish", "boot": "b",
                      "gen": 3, "children": 0})
        rows = t.rows()
        assert [r["id"] for r in rows] == ["r1", "r2"]
        assert all(r["age_s"] >= 0.0 for r in rows)
        assert t.count() == 2
        time.sleep(0.35)
        assert t.rows() == []  # TTL-pruned
        assert t.count() == 0

    def test_pick_least_loaded_fresh_same_boot(self):
        t = _RelayTable(ttl_s=10.0)
        t.beat("busy", {"addr": "http://busy", "boot": "b",
                        "gen": 5, "children": 7})
        t.beat("idle", {"addr": "http://idle", "boot": "b",
                        "gen": 5, "children": 1})
        t.beat("lagging", {"addr": "http://lag", "boot": "b",
                           "gen": 2, "children": 0})  # > 1 gen behind
        t.beat("otherlife", {"addr": "http://ob", "boot": "x",
                             "gen": 5, "children": 0})  # old boot
        assert t.pick("b", 5) == "http://idle"
        # nobody steerable: a fresh-boot head with an empty-enough table
        assert t.pick("nosuchboot", 5) is None

    def test_pick_spreads_between_beats_and_resets_on_beat(self):
        t = _RelayTable(ttl_s=10.0)
        t.beat("r1", {"addr": "http://r1", "boot": "b",
                      "gen": 1, "children": 0})
        t.beat("r2", {"addr": "http://r2", "boot": "b",
                      "gen": 1, "children": 0})
        # four steers between beats alternate instead of dog-piling
        got = sorted(t.pick("b", 1) for _ in range(4))
        assert got == ["http://r1", "http://r1",
                       "http://r2", "http://r2"]
        # a fresh beat resets r1's between-beat assignment counter, so
        # it immediately looks emptiest again
        t.beat("r1", {"addr": "http://r1", "boot": "b",
                      "gen": 1, "children": 0})
        assert t.pick("b", 1) == "http://r1"

    def test_pick_excludes_the_requesting_relay(self):
        t = _RelayTable(ttl_s=10.0)
        t.beat("only", {"addr": "http://only", "boot": "b",
                        "gen": 1, "children": 0})
        assert t.pick("b", 1, exclude_id="only") is None
        assert t.pick("b", 1) == "http://only"


@pytest.mark.relay
class TestQuantizedDeltaPublication:
    """The int8+pow2-scale delta wire (``tft-publish-delta-1``): doc
    format, 1/4-byte minimality, bitwise reconstruction, per-leaf crc
    fallback to the exact f32 route, and verbatim relay adoption."""

    def _rig(self, **kw):
        kw.setdefault("keep_generations", 2)
        pub = WeightPublisher(delta=True, **kw)
        srv = PublicationServer(pub, bind_host="127.0.0.1")
        return pub, srv

    def test_delta_doc_format_and_modes(self):
        pub, srv = self._rig()
        try:
            s1 = make_state(seed=31)
            pub.publish(s1, step=1)
            s2 = dict(s1)
            s2["b1"] = s1["b1"] + np.float32(1e-3)
            pub.publish(s2, step=2)
            with urllib.request.urlopen(
                    f"{srv.address()}/2/delta?base=1", timeout=10) as r:
                doc = json.loads(r.read())
            assert doc["format"] == DELTA_FORMAT
            assert doc["generation"] == 2 and doc["base"] == 1
            assert doc["boot"] == pub.head()["boot"]
            assert doc["body_len"] > 0
            modes = {e["key"]: e["mode"] for e in doc["leaves"]}
            assert modes["b1"] == "delta"
            assert all(m == "carry" for k, m in modes.items()
                       if k != "b1")
            ent = next(e for e in doc["leaves"] if e["key"] == "b1")
            for field in ("offset", "nbytes", "size", "seg_elems",
                          "wire_crc32", "base_crc32", "crc32"):
                assert field in ent, field
            # the delta leaf's crc32 IS the full manifest digest: both
            # routes describe the same bits
            mf = json.loads(urllib.request.urlopen(
                f"{srv.address()}/2/manifest", timeout=10).read())
            mf_ent = next(e for e in mf["leaves"] if e["key"] == "b1")
            assert ent["crc32"] == mf_ent["crc32"]
            # unknown base: the subscriber's full-route fallback signal
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"{srv.address()}/2/delta?base=77", timeout=10)
            assert ei.value.code == 404
            # malformed: no base at all
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"{srv.address()}/2/delta", timeout=10)
            assert ei.value.code == 400
        finally:
            srv.shutdown()

    def test_delta_sync_bitwise_and_quarter_bytes(self):
        pub, srv = self._rig()
        dsub = fsub = None
        try:
            rng = np.random.default_rng(32)
            s1 = make_state(seed=32)
            pub.publish(s1, step=1)
            dsub = WeightSubscriber(srv.address(), template(),
                                    retry_policy=fast_policy())
            fsub = WeightSubscriber(srv.address(), template(),
                                    retry_policy=fast_policy(),
                                    delta=False)
            dsub.sync()
            fsub.sync()
            s2 = dict(s1)
            s2["emb"] = (s1["emb"] + np.float32(1e-3)
                         * rng.normal(size=_SIZES["emb"])
                         .astype(np.float32))
            pub.publish(s2, step=2)
            assert dsub.sync() is True
            assert fsub.sync() is True
            dm = dsub.metrics()
            assert dm["serve_delta_syncs"] == 1
            assert dm["serve_delta_leaves_last"] == 1
            assert dm["serve_delta_crc_fallbacks"] == 0
            # wire minimality: int8 + pow2 scales ~ 1/4 of the changed
            # leaves' f32 bytes (publisher-side accounting agrees)
            pm = pub.metrics()
            assert pm["publish_delta_sets"] >= 1
            wire = pm["publish_delta_wire_bytes_last"]
            assert 0 < wire <= 0.27 * pm["publish_delta_bytes_last"]
            assert dm["serve_delta_wire_bytes_total"] == wire
            # reconstruction is BITWISE the published state: the delta
            # subscriber, the full subscriber, and the publisher's
            # retained reconstruction all hold the same bits
            dw, fw = dsub.weights(), fsub.weights()
            assert_bitwise(dw, fw)
            assert_bitwise(dw, pub._head.state)  # noqa: SLF001
        finally:
            for s in (dsub, fsub):
                if s is not None:
                    s.stop()
            srv.shutdown()

    def test_corrupt_delta_wire_falls_back_per_leaf_f32(self):
        """A corrupt wire payload must lose the LEAF, not the sync: the
        wire crc rejects it, the fallback counter ticks, and the leaf
        rides the exact-f32 full route — final bits identical."""
        pub, srv = self._rig()
        sub = None
        try:
            s1 = make_state(seed=33)
            pub.publish(s1, step=1)
            sub = WeightSubscriber(srv.address(), template(),
                                   retry_policy=fast_policy())
            sub.sync()
            s2 = dict(s1)
            s2["w1"] = s1["w1"] * np.float32(1.001)
            pub.publish(s2, step=2)
            # corrupt one byte of the stored delta body in place
            with pub._cond:  # noqa: SLF001 — fault injection
                rec = pub._gens[2]
            ds = pub._delta_set(rec, 1)  # noqa: SLF001
            bad = bytearray(ds.body)
            bad[len(bad) // 2] ^= 0xFF
            rec.deltas[1] = _DeltaSet(ds.doc, bytes(bad))
            assert sub.sync() is True
            m = sub.metrics()
            assert m["serve_delta_crc_fallbacks"] >= 1
            assert m["serve_delta_syncs"] == 0
            assert_bitwise(sub.weights(), pub._head.state)  # noqa: SLF001
        finally:
            if sub is not None:
                sub.stop()
            srv.shutdown()

    def test_missing_delta_set_falls_back_to_full_route(self):
        """A subscriber whose base generation fell out of the retained
        window gets a 404 on the delta route and converges via the full
        manifest/body path — delta is an optimization, never a
        dependency."""
        pub, srv = self._rig(keep_generations=2)
        sub = None
        try:
            s = make_state(seed=34)
            pub.publish(s, step=1)
            sub = WeightSubscriber(srv.address(), template(),
                                   retry_policy=fast_policy())
            sub.sync()
            for g in (2, 3):  # gen 1 (the sub's base) evicts at gen 3
                s = dict(s)
                s["b2"] = s["b2"] + np.float32(g)
                pub.publish(s, step=g)
            assert sub.sync() is True
            assert sub.generation() == 3
            m = sub.metrics()
            assert m["serve_delta_syncs"] == 0  # full route took it
            assert_bitwise(sub.weights(), pub._head.state)  # noqa: SLF001
        finally:
            if sub is not None:
                sub.stop()
            srv.shutdown()

    def test_relay_adopts_delta_verbatim(self):
        """The relay re-serves the root's wire payloads untouched, so a
        grandchild's delta reconstruction is bitwise the ROOT's
        reconstruction (re-encoding would drift: Int8Wire re-encode of
        a reconstruction is not idempotent)."""
        pub, srv = self._rig(keep_generations=3)
        relay = down = None
        try:
            rng = np.random.default_rng(35)
            s1 = make_state(seed=35)
            pub.publish(s1, step=1)
            relay = WeightRelay(srv.address(), template(),
                                bind_host="127.0.0.1",
                                retry_policy=fast_policy(),
                                register=False, name="deltarelay")
            relay.sync()
            down = WeightSubscriber(relay.address(), template(),
                                    retry_policy=fast_policy())
            down.sync()
            s2 = dict(s1)
            s2["head"] = (s1["head"] + np.float32(1e-3)
                          * rng.normal(size=_SIZES["head"])
                          .astype(np.float32))
            pub.publish(s2, step=2)
            assert relay.sync() is True
            assert relay.last_delta() is not None
            assert down.sync() is True
            dm = down.metrics()
            assert dm["serve_delta_syncs"] == 1
            assert dm["serve_delta_crc_fallbacks"] == 0
            # grandchild bits == root publisher's retained bits
            assert_bitwise(down.weights(), pub._head.state)  # noqa: SLF001
            rm = relay.metrics()
            assert rm["relay_serve_delta_requests"] >= 1
            assert rm["relay_serve_delta_bytes_sent"] > 0
        finally:
            for x in (down, relay):
                if x is not None:
                    x.stop()
            srv.shutdown()

    def test_delta_off_publisher_serves_no_delta_routes(self, rig):
        """Default publishers (delta off) never see delta requests: the
        subscriber only tries the delta route when the head advertises
        it."""
        pub, srv, make_sub = rig
        pub.publish(make_state(fill=1), step=1)
        sub = make_sub()  # delta=True default, but head says no
        sub.sync()
        pub.publish(make_state(fill=2), step=2)
        assert sub.sync() is True
        assert pub.metrics()["serve_delta_requests"] == 0
        assert sub.metrics()["serve_delta_syncs"] == 0


@pytest.mark.relay
class TestRelaySteering:
    """Relay registration beats, the ``/publish/relays`` surface, and
    head-fetch-time subscriber steering (live-relay hints, dead-hint
    cooldown, TTL expiry, death re-parenting)."""

    def test_beat_route_and_relays_endpoint(self, rig):
        pub, srv, _ = rig
        pub.publish(make_state(fill=1), step=1)
        boot = pub.head()["boot"]
        q = urllib.parse.urlencode(
            [("id", "r1"), ("addr", "http://x:1/publish"),
             ("boot", boot), ("gen", "1"), ("step", "1"),
             ("children", "2"), ("bytes_sent", "5")])
        with urllib.request.urlopen(
                f"{srv.address()}/relay/beat?{q}", timeout=10) as r:
            ack = json.loads(r.read())
        assert ack["ok"] is True and ack["relays"] == 1
        with urllib.request.urlopen(
                f"{srv.address()}/relays", timeout=10) as r:
            doc = json.loads(r.read())
        (row,) = doc["relays"]
        assert row["id"] == "r1" and row["lag_gens"] == 0
        assert row["age_s"] >= 0.0
        # malformed beat (no id) is a client error, not a crash
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{srv.address()}/relay/beat?addr=http://x:1", timeout=10)
        assert ei.value.code == 400
        m = pub.metrics()
        assert m["relay_beats"] == 1
        assert m["relays_live"] == 1
        assert m["relay_children_total"] == 2

    def test_subscriber_steered_to_live_relay(self, rig):
        pub, srv, make_sub = rig
        pub.publish(make_state(fill=1), step=1)
        relay = WeightRelay(srv.address(), template(),
                            bind_host="127.0.0.1",
                            retry_policy=fast_policy(),
                            beat_interval_s=0.1,
                            poll_interval_s=0.05,
                            name="steer-r1")
        try:
            relay.sync()
            relay.start()
            deadline = time.monotonic() + 5.0
            while not pub.relay_rows():
                assert time.monotonic() < deadline, "relay never beat in"
                time.sleep(0.02)
            sub = make_sub()
            assert sub.sync() is True
            # the head hint re-parented the sub onto the relay
            assert sub._parents[0] == relay.address()  # noqa: SLF001
            assert sub.metrics()["serve_steers"] >= 1
            assert pub.metrics()["relay_steers"] >= 1
            # the next generation flows through the relay, not the root
            pub.publish(make_state(fill=2), step=2)
            deadline = time.monotonic() + 5.0
            while relay.generation() < 2:
                assert time.monotonic() < deadline
                time.sleep(0.02)
            assert sub.sync() is True
            assert sub.generation() == 2
            assert relay.metrics()["relay_serve_requests"] >= 1
            assert_bitwise(sub.weights(), make_state(fill=2))
        finally:
            relay.stop()

    def test_dead_hint_cools_down_and_root_serves(self, rig):
        """A hint pointing at a dead relay must cost one failover, not
        the sync: the subscriber rotates back to the root, remembers
        the bad address for the cooldown window, and converges."""
        pub, srv, make_sub = rig
        pub.publish(make_state(fill=1), step=1)
        # hand-beat a corpse into the table (port 1: refused fast)
        pub.relay_beat({"id": "corpse",
                        "addr": "http://127.0.0.1:1/publish",
                        "boot": pub.head()["boot"],
                        "gen": 1, "children": 0})
        sub = make_sub()
        assert sub.sync() is True
        m = sub.metrics()
        assert m["serve_steers"] >= 1
        assert m["serve_parent_failovers"] >= 1
        cur = sub._parents[sub._parent_idx  # noqa: SLF001
                           % len(sub._parents)]  # noqa: SLF001
        assert cur == srv.address().rstrip("/")
        assert "http://127.0.0.1:1/publish" in sub._steer_bad  # noqa: SLF001
        assert_bitwise(sub.weights(), make_state(fill=1))
        # still on cooldown: the next sync ignores the lingering row
        # (its TTL has not expired) instead of bouncing off it again
        pub.publish(make_state(fill=2), step=2)
        assert sub.sync() is True
        assert sub.metrics()["serve_parent_failovers"] == \
            m["serve_parent_failovers"]
        assert_bitwise(sub.weights(), make_state(fill=2))

    def test_registration_ttl_expires_dead_relay(self):
        pub = WeightPublisher(keep_generations=2, relay_ttl_s=0.3)
        srv = PublicationServer(pub, bind_host="127.0.0.1")
        relay = None
        try:
            pub.publish(make_state(fill=1), step=1)
            relay = WeightRelay(srv.address(), template(),
                                bind_host="127.0.0.1",
                                retry_policy=fast_policy(),
                                beat_interval_s=0.1,
                                poll_interval_s=0.05,
                                name="ttl-r1")
            relay.sync()
            relay.start()
            deadline = time.monotonic() + 5.0
            while not pub.relay_rows():
                assert time.monotonic() < deadline, "relay never beat in"
                time.sleep(0.02)
            assert relay.metrics()["relay_beats_sent"] >= 1
            relay.stop()
            relay = None
            time.sleep(0.5)  # > ttl with no beats
            assert pub.relay_rows() == []
            assert pub.metrics()["relays_live"] == 0
        finally:
            if relay is not None:
                relay.stop()
            srv.shutdown()

    def test_relay_death_mid_delta_reparents_subscriber(self):
        """Kill the relay a steered subscriber is attached to, mid
        delta stream: the sub's parent rotation walks it back to the
        root and the next delta generation lands bitwise — no torn
        observation, no stall."""
        pub = WeightPublisher(keep_generations=3, delta=True)
        srv = PublicationServer(pub, bind_host="127.0.0.1")
        relay = sub = None
        try:
            rng = np.random.default_rng(36)
            s1 = make_state(seed=36)
            pub.publish(s1, step=1)
            relay = WeightRelay(srv.address(), template(),
                                bind_host="127.0.0.1",
                                retry_policy=fast_policy(),
                                beat_interval_s=0.1,
                                poll_interval_s=0.05,
                                name="doomed-r1")
            relay.sync()
            relay.start()
            deadline = time.monotonic() + 5.0
            while not pub.relay_rows():
                assert time.monotonic() < deadline
                time.sleep(0.02)
            sub = WeightSubscriber(srv.address(), template(),
                                   retry_policy=fast_policy(),
                                   stall_timeout_sec=10.0)
            sub.sync()
            assert sub._parents[0] == relay.address()  # noqa: SLF001
            # one delta generation THROUGH the relay first
            s2 = dict(s1)
            s2["w2"] = (s1["w2"] + np.float32(1e-3)
                        * rng.normal(size=_SIZES["w2"])
                        .astype(np.float32))
            pub.publish(s2, step=2)
            deadline = time.monotonic() + 5.0
            while relay.generation() < 2:
                assert time.monotonic() < deadline
                time.sleep(0.02)
            sub.sync()
            assert sub.metrics()["serve_delta_syncs"] >= 1
            # kill it; the table row ages out while the sub fails over
            relay.stop()
            relay = None
            s3 = dict(s2)
            s3["w2"] = (s2["w2"] + np.float32(1e-3)
                        * rng.normal(size=_SIZES["w2"])
                        .astype(np.float32))
            pub.publish(s3, step=3)
            assert sub.sync() is True
            assert sub.generation() == 3
            m = sub.metrics()
            assert m["serve_parent_failovers"] >= 1
            assert_bitwise(sub.weights(), pub._head.state)  # noqa: SLF001
        finally:
            if sub is not None:
                sub.stop()
            if relay is not None:
                relay.stop()
            srv.shutdown()

    def test_request_stop_unblocks_long_poll(self, rig):
        pub, srv, make_sub = rig
        pub.publish(make_state(fill=1), step=1)
        sub = make_sub(poll_interval_s=0.05)
        sub.sync()
        sub.start()
        time.sleep(0.2)  # loop is long-polling for gen 2
        t0 = time.monotonic()
        sub.request_stop()
        sub.stop()
        assert time.monotonic() - t0 < 3.0


@pytest.mark.relay
@pytest.mark.slow
@pytest.mark.nightly
class TestSteeredDeltaChurnSoak:
    """Nightly soak of the whole CDN stack at once: a delta-mode root,
    registered relays beating into the steering table, subscribers that
    arrive knowing only the root and get steered out, serve-channel
    chaos, a relay killed mid-stream (its table row keeps advertising
    it — steered subs must bounce off, cool down, and converge via the
    root), and subscriber churn. Uniform fill states shift every leaf
    by exactly 1.0 per generation, which the pow2-scale int8 wire
    quantizes EXACTLY, so the fill-uniformity torn check and the final
    bitwise oracle both stay valid under quantized deltas."""

    def test_steered_delta_churn_soak(self):
        sched = ChaosSchedule(seed=1907, endpoints={
            "serve": EndpointChaos(reset_rate=0.04, short_rate=0.06),
        })
        chaos_mod.install(sched)
        pub = WeightPublisher(keep_generations=3, delta=True,
                              relay_ttl_s=1.5)
        srv = PublicationServer(pub, bind_host="127.0.0.1")
        relays = [WeightRelay(srv.address(), template(),
                              bind_host="127.0.0.1",
                              retry_policy=fast_policy(),
                              poll_interval_s=0.05,
                              beat_interval_s=0.2,
                              name=f"steer-soak-relay{i}").start()
                  for i in range(2)]
        deadline = time.monotonic() + 10.0
        while len(pub.relay_rows()) < 2:
            assert time.monotonic() < deadline, "relays never registered"
            time.sleep(0.05)
        # every subscriber knows ONLY the root; steering spreads them
        subs = [WeightSubscriber(
                    srv.address(), template(),
                    retry_policy=fast_policy(), poll_interval_s=0.05,
                    name=f"steer-soak-sub{i}").start()
                for i in range(4)]
        published = set()
        torn: list = []

        def check(sub):
            try:
                tree = sub.weights()
            except StaleWeightsError:
                return
            vals = {k: tree[k][0] for k in _SIZES}
            first = next(iter(vals.values()))
            if not all(v == first for v in vals.values()) \
                    or int(first) not in published:
                torn.append((sub._name, vals))

        try:
            final_gen = 14
            for g in range(1, final_gen + 1):
                pub.publish(make_state(fill=g), step=g)
                published.add(g)
                for s in subs:
                    check(s)
                if g == 5:
                    # kill relay 0's serve plane mid-stream; its beats
                    # keep flowing, so the table still advertises it —
                    # steered subs must bounce off and cool down
                    sched.kill_endpoint(_serve_endpoint(
                        relays[0].address()))
                if g == 8:
                    subs[0].stop()
                    subs[0] = WeightSubscriber(
                        srv.address(), template(),
                        retry_policy=fast_policy(),
                        poll_interval_s=0.05,
                        name="steer-soak-sub0b").start()
                if g == 10:
                    sched.revive_endpoint(_serve_endpoint(
                        relays[0].address()))
                time.sleep(0.25)
            # the pow2 wire kept every generation exact
            assert_bitwise(pub._head.state,  # noqa: SLF001
                           make_state(fill=final_gen))
            deadline = time.monotonic() + 90
            expected = make_state(fill=final_gen)
            for s in subs:
                while True:
                    check(s)
                    if s.generation() == final_gen:
                        break
                    assert time.monotonic() < deadline, \
                        f"{s._name} never converged " \
                        f"(at gen {s.generation()})"
                    time.sleep(0.1)
                assert_bitwise(s.weights(), expected)
            assert not torn, f"torn/unpublished trees observed: {torn}"
            assert sched.fault_count() > 0
            # the stack actually exercised its new machinery
            assert pub.metrics()["relay_beats"] > 0
            assert pub.metrics()["relay_steers"] > 0
            assert sum(s.metrics()["serve_delta_syncs"]
                       for s in subs) > 0
        finally:
            chaos_mod.uninstall()
            for s in subs:
                s.stop()
            for r in relays:
                r.stop()
            srv.shutdown()
