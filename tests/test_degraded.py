"""Degraded-mode groups tests (docs/design/degraded_mode.md).

Tier-1 (marker ``degrade``, ``scripts/test.sh degrade``): submesh
derivation from a live-device set, sharding re-derivation fallbacks,
the weighted canonical-order fold over real socketpair rings (bitwise
against a single-process numpy oracle at worlds 2/3, int8 rung
included), weight-mode skew detection, the chaos ``device`` channel,
the Manager's degrade -> restore lifecycle (commit-boundary discipline,
refusals, flight dumps, the atomic capacity-bearing
``participant_slot`` snapshot), ElasticSampler capacity draws, the
Manager-level weighted pipeline over a pair hub, and the
DegradedModeDriver end-to-end re-``pjit`` lifecycle on the virtual CPU
mesh.

The 2-group chip-loss goodput soak (the >= 70%-of-healthy acceptance
gate, bench row ``degraded_goodput_ab``) needs the native control
plane and rides ``nightly``+``slow``.
"""

import threading
from concurrent.futures import Future
from unittest.mock import MagicMock

import numpy as np
import pytest

import conftest
from torchft_tpu import chaos
from torchft_tpu._native import QuorumResult
from torchft_tpu.backends.host import HostCommunicator, _Ring
from torchft_tpu.communicator import (CommunicatorError,
                                      DummyCommunicator, Int8Wire,
                                      _upcast_buffers, shard_bounds)
from torchft_tpu.degraded import DegradedModeDriver, live_devices
from torchft_tpu.manager import Manager

pytestmark = pytest.mark.degrade

requires_native = conftest.requires_native()


# --------------------------------------------------------------- helpers


def quorum_result(
    quorum_id=1,
    recover_manager_address="manager1:1234",
    store_address="",
    max_step=1,
    max_rank=0,
    max_world_size=1,
    replica_rank=0,
    replica_world_size=1,
    heal=False,
):
    return QuorumResult(
        quorum_id=quorum_id,
        recover_manager_address=recover_manager_address,
        store_address=store_address,
        max_step=max_step,
        max_rank=max_rank,
        max_world_size=max_world_size,
        replica_rank=replica_rank,
        replica_world_size=replica_world_size,
        heal=heal,
    )


def make_manager(client=None, comm=None, **kwargs):
    if client is None:
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = True
    return Manager(
        comm=comm or DummyCommunicator(),
        load_state_dict=kwargs.pop("load_state_dict", MagicMock()),
        state_dict=kwargs.pop("state_dict", lambda: {"w": np.ones(2)}),
        min_replica_size=kwargs.pop("min_replica_size", 1),
        rank=0,
        world_size=1,
        replica_id=kwargs.pop("replica_id", "degradetest"),
        degraded_mode=kwargs.pop("degraded_mode", True),
        _manager_client=client,
        **kwargs,
    )


def weighted_oracle(xs, weights, dtype=np.float32):
    """The documented weighted-fold contract, spelled in single-process
    numpy: sum of w_r * x_r in rank order (zero-weight contributions
    EXCLUDED, not multiplied by zero), true-divided by the total."""
    dt = np.dtype(dtype)
    acc = np.zeros(np.ravel(xs[0]).size, dt)
    for w, x in zip(weights, xs):
        if w:
            acc += np.ravel(x).astype(dt) * dt.type(w)
    total = sum(weights)
    if total:
        acc /= dt.type(total)
    return acc


# ------------------------------------------------------ submesh + specs


class TestSurvivingSubmesh:
    def _mesh(self, shape, n=None):
        import jax

        from torchft_tpu.parallel.mesh import make_mesh

        devs = jax.devices()[: n or int(np.prod(list(shape.values())))]
        return make_mesh(shape, devices=devs)

    def test_full_set_returns_mesh_unchanged(self):
        mesh = self._mesh({"dp": 4})
        from torchft_tpu.parallel.mesh import surviving_submesh

        sub, frac = surviving_submesh(mesh, list(mesh.devices.flat))
        assert sub is mesh and frac == 1.0

    def test_lost_chip_drops_its_data_slice_only(self):
        from torchft_tpu.parallel.mesh import surviving_submesh

        mesh = self._mesh({"dp": 4, "tp": 2})
        devs = list(mesh.devices.flat)
        sub, frac = surviving_submesh(mesh, [d for d in devs
                                             if d != devs[3]])
        # The lost chip sits in dp slice 1; tp survives whole.
        assert frac == 0.75
        assert dict(sub.shape) == {"dp": 3, "tp": 2}
        assert devs[3] not in set(sub.devices.flat)

    def test_two_lost_chips_same_slice_cost_one_slice(self):
        from torchft_tpu.parallel.mesh import surviving_submesh

        mesh = self._mesh({"dp": 4, "tp": 2})
        devs = np.asarray(mesh.devices)
        live = [d for d in devs.flat
                if d not in set(devs[1].flat)]  # both chips of slice 1
        sub, frac = surviving_submesh(mesh, live)
        assert frac == 0.75 and dict(sub.shape) == {"dp": 3, "tp": 2}

    def test_shrink_axis_selectable(self):
        from torchft_tpu.parallel.mesh import surviving_submesh

        mesh = self._mesh({"tp": 2, "dp": 4})
        devs = list(mesh.devices.flat)
        sub, frac = surviving_submesh(mesh, devs[:-1],
                                      shrink_axis="dp")
        assert frac == 0.75 and dict(sub.shape) == {"tp": 2, "dp": 3}

    def test_no_surviving_slice_raises(self):
        from torchft_tpu.parallel.mesh import surviving_submesh

        mesh = self._mesh({"dp": 2, "tp": 4})
        devs = np.asarray(mesh.devices)
        # One chip of EACH dp slice lost -> no full slice survives.
        live = [d for d in devs.flat
                if d not in (devs[0, 0], devs[1, 1])]
        with pytest.raises(ValueError, match="no full slice"):
            surviving_submesh(mesh, live)


class TestDegradedShardings:
    def test_rule_that_no_longer_divides_falls_back(self):
        import jax
        from jax.sharding import PartitionSpec

        from torchft_tpu.parallel.mesh import make_mesh
        from torchft_tpu.parallel.sharding import degraded_shardings

        sub = make_mesh({"dp": 3}, devices=jax.devices()[:3])
        tree = {"w": np.zeros((8, 4096), np.float32),
                "b": np.zeros(4096, np.float32)}
        # dim 0 (=8) divided dp=4 on the full mesh but not dp=3: the
        # rule falls back (here to inferred replication/FSDP) instead
        # of raising — chip loss must not be fatal.
        sh = degraded_shardings(
            tree, sub, rules=((r"w", PartitionSpec("dp", None)),),
            fsdp_axis="dp")
        assert sh["w"].spec != PartitionSpec("dp", None)
        # A leaf the shrunken axis still divides keeps real sharding.
        sh2 = degraded_shardings(
            {"v": np.zeros((6, 2048), np.float32)}, sub,
            rules=((r"v", PartitionSpec("dp", None)),), fsdp_axis="dp")
        assert sh2["v"].spec == PartitionSpec("dp", None)


# ------------------------------------------------- weighted fold (ring)


def _socketpair_rings(world):
    import socket as _socket

    pairs = [_socket.socketpair() for _ in range(world)]
    return [_Ring(pairs[r][0], pairs[(r - 1) % world][1],
                  _socket.socket())
            for r in range(world)]


class TestWeightedFoldRing:
    """The weighted canonical-order fold over real sockets — the
    numeric heart of degraded mode: 2 groups with skewed contributions
    must produce the bitwise-identical weighted average on every rank,
    matching a single-process numpy oracle."""

    def _run(self, world, fn):
        rings = _socketpair_rings(world)
        comms = []
        for r in range(world):
            c = HostCommunicator(timeout_sec=15)
            c._rank, c._world = r, world
            comms.append(c)
        out = [None] * world
        errors = []

        def w(r):
            try:
                out[r] = fn(comms[r], rings[r], r)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        ts = [threading.Thread(target=w, args=(r,)) for r in range(world)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        alive = [t for t in ts if t.is_alive()]
        for ring in rings:
            ring.close()
        for c in comms:
            c.shutdown()
        assert not alive, "weighted ring deadlocked"
        return out, errors

    @pytest.mark.parametrize("world,weights", [
        (2, [48, 16]),   # the 3:1 skew of the acceptance criterion
        (2, [1, 3]),
        (3, [5, 2, 1]),
    ])
    def test_bitwise_matches_numpy_oracle_on_every_rank(self, world,
                                                        weights):
        rng = np.random.default_rng(world)
        xs = [rng.normal(size=10_007).astype(np.float32)
              for _ in range(world)]
        out, errors = self._run(
            world, lambda c, ring, r: c._do_allreduce_wire(
                ring, [xs[r].copy()], [np.dtype(np.float32)], "sum",
                "step", weights[r]))
        assert not errors, errors
        expected = weighted_oracle(xs, weights)
        for o in out:
            np.testing.assert_array_equal(o[0], expected)

    def test_zero_weight_junk_never_poisons(self):
        """A healer's weight-0 contribution is EXCLUDED from the fold,
        not multiplied by zero — NaN * 0 is NaN, so inclusion would let
        one wounded rank poison the average."""
        x0 = np.ones(1_000, np.float32)
        junk = np.full(1_000, np.nan, np.float32)
        out, errors = self._run(
            2, lambda c, ring, r: c._do_allreduce_wire(
                ring, [(x0 if r == 0 else junk).copy()],
                [np.dtype(np.float32)], "sum", "step",
                7 if r == 0 else 0))
        assert not errors, errors
        for o in out:
            np.testing.assert_array_equal(o[0], x0)

    @pytest.mark.parametrize("world", [2, 3])
    def test_int8_rung_weighted_fold(self, world):
        rng = np.random.default_rng(17 + world)
        xs = [rng.normal(size=9_001).astype(np.float32)
              for _ in range(world)]
        weights = [3, 1, 2][:world]
        ws = [Int8Wire.quantize(x) for x in xs]
        out, errors = self._run(
            world, lambda c, ring, r: c._do_allreduce_wire(
                ring, [Int8Wire.quantize(xs[r])],
                [np.dtype(np.float32)], "sum", "step", weights[r]))
        assert not errors, errors
        expected = weighted_oracle(
            [w.dequantize(np.float32) for w in ws], weights)
        for o in out:
            np.testing.assert_array_equal(o[0], expected)

    @pytest.mark.parametrize("world", [2, 3])
    def test_reduce_scatter_stripes_match_allreduce(self, world):
        rng = np.random.default_rng(23)
        xs = [rng.normal(size=9_001).astype(np.float32)
              for _ in range(world)]
        weights = [4, 1, 2][:world]
        full, errors = self._run(
            world, lambda c, ring, r: c._do_allreduce_wire(
                ring, [xs[r].copy()], [np.dtype(np.float32)], "sum",
                "step", weights[r]))
        assert not errors, errors
        shards, errors = self._run(
            world, lambda c, ring, r: c._do_reduce_scatter_wire(
                ring, [xs[r].copy()], [np.dtype(np.float32)], "sum",
                "step", weights[r]))
        assert not errors, errors
        bounds = shard_bounds(9_001, world)
        for r in range(world):
            np.testing.assert_array_equal(
                shards[r][0], full[0][0][bounds[r]:bounds[r + 1]])

    def test_weight_mode_skew_aborts_cleanly(self):
        """The wire-v4 skew guarantee of the acceptance criteria: a
        rank folding weighted while its peer folds uniform must get a
        clean CommunicatorError from the preamble — never a silently
        different fold on each side."""
        x = np.ones(4_096, np.float32)
        out, errors = self._run(
            2, lambda c, ring, r: c._do_allreduce_wire(
                ring, [x.copy()], [np.dtype(np.float32)], "sum",
                "step", 8 if r == 0 else -1))
        assert len(errors) == 2, (errors, out)
        for e in errors:
            assert isinstance(e, CommunicatorError)
            assert "wire weight skew" in str(e)

    def test_geometry_skew_still_aborts_with_weights(self):
        """Weights ride the same preamble as the format hash — a
        geometry mismatch under weighted mode stays a clean abort."""
        out, errors = self._run(
            2, lambda c, ring, r: c._do_allreduce_wire(
                ring,
                [np.ones(1_024 if r == 0 else 2_048, np.float32)],
                [np.dtype(np.float32)], "sum", "step", 4))
        assert len(errors) == 2, (errors, out)
        assert all("wire format skew" in str(e) for e in errors)

    def test_bf16_wire_weighted(self):
        """Narrow wire dtypes keep the one-quantization contract under
        weights: the fold upcasts the raw bf16 contributions, weights,
        and normalizes — bitwise across ranks and vs the oracle over
        the quantized values."""
        import jax.numpy as jnp

        wdt = np.dtype(jnp.bfloat16)
        rng = np.random.default_rng(4)
        xs = [rng.normal(size=2_048).astype(np.float32)
              for _ in range(2)]
        bf = [x.astype(wdt) for x in xs]
        weights = [3, 1]
        out, errors = self._run(
            2, lambda c, ring, r: c._do_allreduce_wire(
                ring, [bf[r].copy()], [np.dtype(np.float32)], "sum",
                "step", weights[r]))
        assert not errors, errors
        expected = weighted_oracle(
            [b.astype(np.float32) for b in bf], weights)
        for o in out:
            np.testing.assert_array_equal(o[0], expected)


# ----------------------------------------------------- device chaos


class TestDeviceChaosChannel:
    def test_spec_parsable(self):
        s = chaos.parse_spec(
            "seed=9;device:chip_loss_rate=0.5,chip_return_rate=0.25")
        cfg = s.config_for("device:g0")
        assert cfg.chip_loss_rate == 0.5
        assert cfg.chip_return_rate == 0.25

    def test_seeded_event_stream_is_deterministic(self):
        def drive(seed):
            s = chaos.ChaosSchedule(seed=seed, endpoints={
                "device": chaos.EndpointChaos(chip_loss_rate=0.4,
                                              chip_return_rate=0.3)})
            return [tuple(sorted(chaos.device_fault("device:gA", 8, s)))
                    for _ in range(40)]

        assert drive(11) == drive(11)
        assert drive(11) != drive(12)

    def test_never_loses_the_last_chip(self):
        s = chaos.ChaosSchedule(seed=1, endpoints={
            "device": chaos.EndpointChaos(chip_loss_rate=1.0)})
        for _ in range(30):
            lost = chaos.device_fault("device:g", 4, s)
        assert len(lost) == 3  # one survivor, always

    def test_chip_return_revives(self):
        s = chaos.ChaosSchedule(seed=2)
        s.lose_chip("device:g", 1)
        s.lose_chip("device:g", 3)
        assert s.lost_chips("device:g") == frozenset({1, 3})
        s.return_chip("device:g", 3)
        assert s.lost_chips("device:g") == frozenset({1})

    def test_intensity_zero_freezes_events(self):
        """PhasedChaos drives the channel through stable phases: at
        intensity 0 the decision stream keeps drawing (determinism) but
        no chip events fire."""
        from torchft_tpu.policy import PhasedChaos

        s = chaos.ChaosSchedule(seed=3, endpoints={
            "device": chaos.EndpointChaos(chip_loss_rate=1.0)})
        PhasedChaos(s, ((1e9, 0.0),)).tick()
        for _ in range(10):
            assert chaos.device_fault("device:g", 8, s) == frozenset()

    def test_live_devices_applies_lost_set(self):
        s = chaos.ChaosSchedule(seed=4)
        s.lose_chip("device:r0", 0)
        devs = ["d0", "d1", "d2"]
        assert live_devices("r0", devs, s) == ["d1", "d2"]
        assert live_devices("other", devs, s) == devs


# ------------------------------------------------- manager lifecycle


class TestManagerDegradedLifecycle:
    def test_requires_degraded_mode(self):
        m = make_manager(degraded_mode=False)
        try:
            with pytest.raises(RuntimeError, match="degraded_mode"):
                m.request_degrade(0.5)
            with pytest.raises(RuntimeError, match="degraded_mode"):
                m.request_restore()
        finally:
            m.shutdown()

    def test_fraction_validation(self):
        m = make_manager()
        try:
            with pytest.raises(ValueError, match="fraction"):
                m.request_degrade(0.0)
            with pytest.raises(ValueError, match="fraction"):
                m.request_degrade(1.5)
        finally:
            m.shutdown()

    def test_degrade_restore_counters_and_events(self):
        m = make_manager()
        try:
            assert m.request_degrade(0.5, samples=16)
            assert m.capacity_fraction() == 0.5
            mx = m.metrics()
            assert mx["degraded_capacity_fraction"] == 0.5
            assert mx["degrade_events_total"] == 1
            assert m.request_restore()
            mx = m.metrics()
            assert mx["degraded_capacity_fraction"] == 1.0
            assert mx["restore_events_total"] == 1
            events = [e.get("event") for e in m.history()]
            assert "degrade" in events and "restore" in events
        finally:
            m.shutdown()

    def test_refused_mid_deferred_and_mid_heal_and_errored(self):
        m = make_manager()
        try:
            f = Future()
            f.set_result({"g": np.zeros(2)})
            m.stage_deferred(f)
            assert not m.request_degrade(0.5)
            m.drain_deferred()
            with m._metrics_lock:
                m._healing = True
            assert not m.request_degrade(0.5)
            with m._metrics_lock:
                m._healing = False
            m.report_error(RuntimeError("boom"))
            assert not m.request_restore()
            assert m.capacity_fraction() == 1.0
            refused = [e for e in m.history()
                       if str(e.get("event", "")).endswith("_refused")]
            assert len(refused) == 3
        finally:
            m.shutdown()

    def test_flight_dump_on_every_capacity_transition(self, tmp_path,
                                                      monkeypatch):
        import json
        import os

        monkeypatch.setenv("TORCHFT_FLIGHT_DIR", str(tmp_path))
        m = make_manager(replica_id="cap0")
        try:
            m.step()
            assert m.request_degrade(0.5)
            assert m.request_restore()
            files = sorted(os.listdir(tmp_path))
            assert any("degrade" in f for f in files), files
            assert any("restore" in f for f in files), files
            body = json.loads(
                (tmp_path / next(f for f in files
                                 if "degrade" in f)).read_text())
            assert body["torchft"]["extra"]["to"] == 0.5
            assert body["traceEvents"] is not None
        finally:
            m.shutdown()

    def test_participant_slot_carries_capacity_atomically(self):
        """The satellite regression: rank and capacity are one
        lock-consistent snapshot — a reader can never observe the new
        capacity with the old rank or vice versa."""
        m = make_manager()
        stop = threading.Event()

        def writer():
            flip = False
            while not stop.is_set():
                with m._metrics_lock:
                    if flip:
                        m._participating_rank = 1
                        m._capacity_fraction = 0.5
                    else:
                        m._participating_rank = 0
                        m._capacity_fraction = 1.0
                flip = not flip

        t = threading.Thread(target=writer)
        t.start()
        try:
            for _ in range(3_000):
                rank, _bc, frac = m.participant_slot()
                assert (rank, frac) in ((0, 1.0), (1, 0.5)), (rank, frac)
        finally:
            stop.set()
            t.join(timeout=5)
            m.shutdown()

    def test_snapshot_joins_inflight_quorum(self):
        """The PR-1 residual torn window is closed: a draw between
        step() and the async quorum resolving now reflects the POST-
        quorum membership, never the previous quorum's rank."""
        import time as _time

        client = MagicMock()

        def slow_quorum(**kwargs):
            _time.sleep(0.3)
            return quorum_result(max_rank=1, replica_rank=1,
                                 max_world_size=2,
                                 replica_world_size=2)

        client.quorum.side_effect = slow_quorum
        client.should_commit.return_value = True
        m = make_manager(client=client)
        try:
            m.step()
            rank, bc, frac = m.participant_slot()  # must wait the round
            assert rank == 1
        finally:
            m.shutdown()

    def test_capacity_advertised_on_quorum_store(self):
        store = MagicMock()
        m = make_manager()
        try:
            m._healset_store = ("fake:0", store)
            m.request_degrade(0.25)
            q = quorum_result(store_address="fake:0", max_world_size=2,
                              replica_world_size=2, replica_rank=1)
            m._publish_capacity(q)
            store.set.assert_called_with(
                "torchft/capacity/1", f"{m.current_step()}:0.25".encode())
        finally:
            m.shutdown()

    def test_wire_weight_zero_while_not_participating(self):
        m = make_manager()
        try:
            m.request_degrade(0.5, samples=24)
            assert m._wire_weight() == 24
            with m._metrics_lock:
                m._healing = True
            assert m._wire_weight() == 0
        finally:
            m.shutdown()


# ----------------------------------------------- sampler capacity


class _FakeSlotManager:
    def __init__(self, rank=0, bc=0, frac=1.0):
        self.rank, self.bc, self.frac = rank, bc, frac
        self.reported = []

    def participant_slot(self):
        return self.rank, self.bc, self.frac

    def set_step_samples(self, n):
        self.reported.append(n)


class TestElasticSamplerCapacity:
    def test_degraded_draw_shrinks_and_reports(self):
        from torchft_tpu.data import ElasticSampler

        m = _FakeSlotManager(rank=1, bc=4, frac=0.5)
        s = ElasticSampler(64, m, batch_size=8, seed=0)
        idx = s.next_indices()
        assert len(idx) == 4
        assert m.reported == [4]
        # The shrunken draw is the PREFIX of the full slot's batch.
        np.testing.assert_array_equal(idx, s.indices_for_slot(5)[:4])

    def test_full_capacity_unchanged(self):
        from torchft_tpu.data import ElasticSampler

        m = _FakeSlotManager(rank=0, bc=2, frac=1.0)
        s = ElasticSampler(64, m, batch_size=8, seed=0)
        idx = s.next_indices()
        assert len(idx) == 8
        assert m.reported == [8]

    def test_two_tuple_snapshot_back_compat(self):
        """Duck-typed managers returning the pre-capacity 2-tuple keep
        working (capacity defaults to 1.0)."""
        from torchft_tpu.data import ElasticSampler

        class Legacy:
            def participant_slot(self):
                return 1, 10

        s = ElasticSampler(64, Legacy(), batch_size=4, seed=0)
        np.testing.assert_array_equal(
            s.next_indices(), s.indices_for_slot(11))

    def test_elastic_loader_keys_cache_by_capacity(self):
        from torchft_tpu.data import ElasticLoader, ElasticSampler

        class DS:
            def __init__(self):
                self.reads = 0

            def __len__(self):
                return 64

            def __getitem__(self, idx):
                self.reads += 1
                return {"x": np.asarray(idx)}

        m = _FakeSlotManager(rank=0, bc=0, frac=1.0)
        m.num_participants = lambda: 1
        ds = DS()
        loader = ElasticLoader(ds, ElasticSampler(64, m, batch_size=8),
                               prefetch=0)
        full = loader()
        assert len(full["x"]) == 8
        m.frac = 0.5  # capacity transition: same slot, shrunken draw
        half = loader()
        assert len(half["x"]) == 4
        assert m.reported[-1] == 4


# ------------------------------------- manager-level weighted pipeline


class _WeightedHub:
    """Two-rank wire-op rendezvous that folds contributions with the
    weighted canonical-order contract (the pair-hub pattern of
    test_policy, grown a weight column): exercises the Manager's
    weight capture (set_wire_weight per op) and its skipped 1/n in
    degraded mode without the native control plane."""

    def __init__(self, world=2):
        self.lock = threading.Lock()
        self.world = world
        self.counts = {}
        self.pending = {}

    def submit(self, rank, buffers, origs, weight):
        fut = Future()
        with self.lock:
            idx = self.counts.get(rank, 0)
            self.counts[rank] = idx + 1
            entry = self.pending.setdefault(idx, {})
            entry[rank] = (list(buffers),
                           [np.dtype(d) for d in origs],
                           int(weight), fut)
            ready = len(entry) == self.world
            if ready:
                del self.pending[idx]
        if ready:
            weights = {r: w for r, (_b, _o, w, _f) in entry.items()}
            assert all(w >= 0 for w in weights.values()), weights
            vals = {r: _upcast_buffers(b, o)
                    for r, (b, o, _w, _f) in entry.items()}
            total = sum(weights.values())
            outs = []
            for i in range(len(vals[0])):
                acc = np.zeros_like(vals[0][i])
                for r in sorted(vals):
                    if weights[r]:
                        acc += vals[r][i] * acc.dtype.type(weights[r])
                if total:
                    acc /= acc.dtype.type(total)
                outs.append(acc)
            for _r, (_b, origs_r, _w, f) in entry.items():
                f.set_result([np.array(s, dtype=d)
                              for s, d in zip(outs, origs_r)])
        return fut


class _WeightedComm(DummyCommunicator):
    def __init__(self, hub, rank):
        super().__init__(rank=rank, world_size=2)
        self._hub = hub

    def allreduce_wire(self, buffers, orig_dtypes, op="sum"):
        return self._hub.submit(self.rank(), buffers, orig_dtypes,
                                getattr(self, "wire_weight", -1))


class TestManagerWeightedPipeline:
    def test_skewed_groups_average_by_samples(self):
        """Two degraded-mode Managers, 3:1 sample skew: the resolved
        average must be the samples-weighted one on BOTH groups, and
        the Manager must not re-divide by the participant count."""
        hub = _WeightedHub()
        rng = np.random.default_rng(0)
        grads = [rng.normal(size=257).astype(np.float32)
                 for _ in range(2)]
        barrier = threading.Barrier(2)
        results = {}
        errors = []

        def run_group(rank):
            client = MagicMock()
            client.quorum.return_value = quorum_result(
                max_rank=rank, replica_rank=rank, max_world_size=2,
                replica_world_size=2)
            client.should_commit.return_value = True
            m = make_manager(client=client,
                             comm=_WeightedComm(hub, rank),
                             replica_id=f"wg{rank}",
                             min_replica_size=2)
            try:
                if rank == 1:
                    assert m.request_degrade(1 / 3, samples=16)
                else:
                    m.set_step_samples(48)
                barrier.wait(timeout=30)
                m.step()
                avg = m.allreduce({"g": grads[rank].copy()}).result()
                assert m.should_commit()
                results[rank] = np.asarray(avg["g"])
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                try:
                    barrier.abort()
                except Exception:  # noqa: BLE001
                    pass
            finally:
                m.shutdown()

        ts = [threading.Thread(target=run_group, args=(r,))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert not errors, errors
        assert len(results) == 2
        expected = weighted_oracle(grads, [48, 16])
        np.testing.assert_array_equal(results[0], expected)
        np.testing.assert_array_equal(results[1], expected)


# ------------------------------------------------ driver end-to-end


class TestDegradedModeDriver:
    def test_degrade_rejoin_restore_lifecycle(self):
        """The full walk on the virtual CPU mesh: lose a chip -> tick
        lands the degrade (capacity, submesh placement, shrunken
        batch) -> training keeps committing -> chip returns -> tick
        restores the full mesh."""
        import jax
        import jax.numpy as jnp
        import optax
        from jax.sharding import NamedSharding

        from torchft_tpu.data import ElasticSampler
        from torchft_tpu.parallel import FTTrainer
        from torchft_tpu.parallel.mesh import make_mesh
        from torchft_tpu.parallel.sharding import (batch_spec,
                                                   combined_shardings)

        devs = jax.devices()[:4]
        mesh = make_mesh({"dp": 4}, devices=devs)
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = True

        def loss_fn(params, batch):
            return ((batch["x"] @ params["w"]) ** 2).mean()

        rng = np.random.default_rng(0)
        xarr = jnp.asarray(rng.normal(size=(64, 6)), jnp.float32)
        params = {"w": np.full((6, 2), 0.1, np.float32)}
        trainer = FTTrainer(
            loss_fn=loss_fn, tx=optax.sgd(0.01), params=params,
            manager_factory=lambda load, save: Manager(
                comm=DummyCommunicator(), load_state_dict=load,
                state_dict=save, min_replica_size=1, rank=0,
                world_size=1, replica_id="drv0", degraded_mode=True,
                _manager_client=client),
            param_shardings=combined_shardings(params, mesh),
            batch_sharding=NamedSharding(mesh, batch_spec(mesh)))
        sampler = ElasticSampler(64, trainer.manager, batch_size=8,
                                 seed=0)
        sched = chaos.ChaosSchedule(seed=0)
        driver = DegradedModeDriver(
            trainer, mesh,
            probe=lambda: live_devices("drv0", devs, sched))
        try:
            def batch():
                return {"x": xarr[sampler.next_indices()]}

            _, committed = trainer.train_step(batch)
            assert committed
            assert not driver.tick()  # all chips live: no transition

            sched.lose_chip("device:drv0", 2)
            assert driver.tick()
            assert trainer.manager.capacity_fraction() == 0.75
            assert driver.fraction() == 0.75
            assert len(trainer.params["w"].sharding.device_set) == 3
            assert devs[2] not in trainer.params["w"].sharding.device_set
            _, committed = trainer.train_step(batch)
            assert committed
            # The shrunken draw landed as the fold weight.
            assert trainer.manager._wire_weight() == 6  # round(8 * .75)

            sched.return_chip("device:drv0", 2)
            assert driver.tick()
            assert trainer.manager.capacity_fraction() == 1.0
            assert len(trainer.params["w"].sharding.device_set) == 4
            _, committed = trainer.train_step(batch)
            assert committed
            mx = trainer.manager.metrics()
            assert mx["degrade_events_total"] == 1
            assert mx["restore_events_total"] == 1
        finally:
            trainer.shutdown()

    def test_tick_retries_after_refusal(self):
        """A transition refused at a bad boundary (deferred in flight)
        lands at the next tick — the save_durable-style retry."""
        import jax

        from torchft_tpu.parallel.mesh import make_mesh

        m = make_manager()
        trainer = MagicMock()
        trainer.manager = m
        mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
        devs = list(mesh.devices.flat)
        driver = DegradedModeDriver(trainer, mesh,
                                    probe=lambda: devs[:3])
        try:
            f = Future()
            f.set_result(None)
            m.stage_deferred(f)
            assert not driver.tick()  # refused: deferred in flight
            assert driver.fraction() == 1.0
            assert not trainer.set_placement.called
            m.drain_deferred()
            assert driver.tick()
            assert driver.fraction() == 0.75
            assert trainer.set_placement.called
        finally:
            m.shutdown()


# ----------------------------------------------------- nightly soak


@pytest.mark.slow
@pytest.mark.nightly
@requires_native
class TestDegradedGoodputSoak:
    def test_goodput_degrades_proportionally_not_in_group_quanta(self):
        """The acceptance gate: a 2-group host-backend run where one
        group loses half its devices mid-run must settle at >= 70% of
        the healthy committed-samples/sec baseline (whole-group
        eviction would cost ~50%)."""
        import bench

        row = bench.bench_degraded_goodput(steps=12)
        assert row["healthy_samples_per_s"] > 0
        assert row["degraded_ratio"] >= 0.70, row
        assert row["eviction_ratio"] == 0.5
