"""Control-plane scaling tests (docs/design/control_plane.md).

Three layers:
  * no-native units — the quorum-latency reservoir and the Manager's
    fast/slow round accounting, driven through a mocked ManagerClient;
  * native-gated protocol tests — piggybacked-beat freshness (the
    standalone heartbeat can be effectively off and the lighthouse still
    sees fresh beats), fast-path hit/epoch accounting through the real
    C++ stack;
  * native-gated failover acceptance — a 2-group training run whose
    PRIMARY lighthouse is SIGKILLed mid-run: managers re-dial the warm
    standby and keep committing with NO ring rebuild (reconfigure_count
    frozen) and NO vote aborts, ending bitwise identical; plus a nightly
    TORCHFT_CHAOS round with the primary black-holed (SIGSTOP — sockets
    alive, nothing answers), the worst-case death shape.

The C++-level unit matrix (cache invalidation per membership-delta class,
epoch monotonicity, fast-vs-slow decision identity) lives in
torchft_tpu/_core/core_test.cc.
"""

import os
import signal
import subprocess
import sys
import threading
import time
from unittest.mock import MagicMock

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import conftest
from torchft_tpu._native import QuorumResult
from torchft_tpu.communicator import DummyCommunicator
from torchft_tpu.manager import Manager, _LatencyReservoir

requires_native = conftest.requires_native()


# ---------------------------------------------------------------- reservoir


@pytest.mark.control_plane
class TestLatencyReservoir:
    def test_bounded_with_exact_max(self):
        r = _LatencyReservoir(size=64, seed=1)
        for i in range(10_000):
            r.add(float(i % 100))
        r.add(12345.0)  # a spike the sampler must never lose
        p = r.percentiles()
        assert len(r._samples) == 64
        assert p["max"] == 12345.0
        assert 0.0 <= p["p50"] <= p["p95"] <= p["max"]

    def test_empty(self):
        assert _LatencyReservoir().percentiles() == {
            "p50": 0.0, "p95": 0.0, "max": 0.0}

    def test_deterministic_given_seed(self):
        a, b = _LatencyReservoir(seed=9), _LatencyReservoir(seed=9)
        for i in range(5000):
            a.add(float(i))
            b.add(float(i))
        assert a.percentiles() == b.percentiles()


# --------------------------------------------------- manager-side accounting


def _quorum_result(step=1, fast_path=False, epoch=0):
    return QuorumResult(
        quorum_id=7, recover_manager_address="m:1", store_address="s:1",
        max_step=step, max_rank=0, max_world_size=2, replica_rank=0,
        replica_world_size=2, heal=False, fast_path=fast_path, epoch=epoch)


def _make_manager(client):
    return Manager(
        comm=DummyCommunicator(), load_state_dict=MagicMock(),
        state_dict=lambda: {"w": np.ones(2)}, min_replica_size=1,
        use_async_quorum=False, rank=0, world_size=1,
        replica_id="cp_test", _manager_client=client)


@pytest.mark.control_plane
class TestManagerControlPlaneMetrics:
    def test_fast_slow_round_split_and_epoch(self):
        client = MagicMock()
        client.quorum.side_effect = [
            _quorum_result(step=1, fast_path=False, epoch=100),
            _quorum_result(step=2, fast_path=True, epoch=101),
            _quorum_result(step=3, fast_path=True, epoch=103),
        ]
        m = _make_manager(client)
        for _ in range(3):
            m.step()
        mx = m.metrics()
        assert mx["quorum_fast_path_hits"] == 2
        assert mx["quorum_slow_path_rounds"] == 1
        assert mx["quorum_epoch_last"] == 103
        assert mx["quorum_count"] == 3
        # Reservoir percentiles ride metrics()/metrics.json.
        assert mx["quorum_ms_max"] >= mx["quorum_ms_p95"] >= mx["quorum_ms_p50"] > 0
        # No native manager server attached -> no redials, key still present.
        assert mx["lighthouse_redials"] == 0.0

    def test_mocked_client_without_new_fields_counts_slow(self):
        # Duck-typed/mocked rigs that predate fast_path/epoch must not
        # crash or miscount as fast hits.
        client = MagicMock()  # quorum() returns a bare MagicMock
        q = client.quorum.return_value
        q.replica_world_size = 2
        q.quorum_id = 3
        q.max_step = 1
        q.replica_rank = 0
        q.max_rank = 0
        q.heal = False
        q.store_address = "s:1"
        m = _make_manager(client)
        m.step()
        mx = m.metrics()
        assert mx["quorum_fast_path_hits"] == 0
        assert mx["quorum_slow_path_rounds"] == 1


# ------------------------------------------------------- native: fast path


@requires_native
@pytest.mark.control_plane
class TestFastPathNative:
    def test_fast_path_hits_and_epochs_via_manager_stack(self):
        """Two groups through the real C++ manager+lighthouse: round 1 is
        the slow rendezvous, steady-state rounds ride the cache."""
        from torchft_tpu._native import Lighthouse, ManagerClient, ManagerServer

        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=2,
                        join_timeout_ms=2000, quorum_tick_ms=10,
                        heartbeat_fresh_ms=300)
        servers, clients = [], []
        try:
            for gid in ("ga", "gb"):
                s = ManagerServer(gid, lh.address(), store_addr=f"st_{gid}",
                                  bind="127.0.0.1:0", world_size=1)
                servers.append(s)
                clients.append(ManagerClient(s.address()))

            results = {}

            def run_round(step):
                def one(i):
                    results[(step, i)] = clients[i].quorum(
                        rank=0, step=step, checkpoint_server_addr=f"c{i}",
                        timeout_ms=20_000)
                ts = [threading.Thread(target=one, args=(i,))
                      for i in range(2)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()

            for step in (1, 2, 3):
                run_round(step)
            assert not results[(1, 0)].fast_path
            assert results[(2, 0)].fast_path and results[(2, 1)].fast_path
            assert results[(3, 0)].fast_path
            # quorum_id frozen (membership unchanged), epoch total order.
            ids = {r.quorum_id for r in results.values()}
            assert len(ids) == 1
            for i in (0, 1):
                epochs = [results[(s, i)].epoch for s in (1, 2, 3)]
                assert epochs == sorted(epochs)
                assert epochs[2] > epochs[0]
            st = lh.status()
            assert st["fast_path_hits"] >= 4
            assert st["slow_path_served"] >= 2
            assert servers[0].lighthouse_redials() == 0
        finally:
            for s in servers:
                s.shutdown()
            lh.shutdown()

    def test_piggybacked_beats_keep_liveness_fresh(self):
        """With the standalone heartbeat effectively disabled (60s
        cadence), quorum-RPC piggybacking alone must keep the lighthouse's
        per-member liveness fresh — the coalesced-heartbeat contract."""
        from torchft_tpu._native import Lighthouse, ManagerClient, ManagerServer

        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=2,
                        join_timeout_ms=2000, quorum_tick_ms=10,
                        heartbeat_fresh_ms=400)
        servers, clients = [], []
        try:
            for gid in ("ga", "gb"):
                s = ManagerServer(gid, lh.address(), store_addr=f"st_{gid}",
                                  bind="127.0.0.1:0", world_size=1,
                                  heartbeat_ms=60_000)
                servers.append(s)
                clients.append(ManagerClient(s.address()))

            for step in (1, 2, 3, 4):
                ts = [threading.Thread(
                    target=lambda i=i, s=step: clients[i].quorum(
                        rank=0, step=s, checkpoint_server_addr=f"c{i}",
                        timeout_ms=20_000)) for i in range(2)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
            # Steps 2-4 rode the fast path: only piggybacked beats could
            # have refreshed the records (the standalone thread fires once
            # a minute).
            st = lh.status()
            ages = {m["replica_id"]: m["heartbeat_age_ms"]
                    for m in st["members"]}
            assert set(ages) == {"ga", "gb"}
            for rid, age in ages.items():
                assert 0 <= age < 2_000, (rid, age)
            assert st["fast_path_hits"] >= 4
        finally:
            for s in servers:
                s.shutdown()
            lh.shutdown()


# --------------------------------------------- native: standby failover E2E


def _spawn_lighthouse_subprocess(tmp_path, *extra_args):
    """Start `python -m torchft_tpu.lighthouse` on an ephemeral port and
    return (proc, address). A real OS process so the test can SIGKILL /
    SIGSTOP it — in-process shutdown is too polite a death."""
    addr_file = os.path.join(str(tmp_path), f"lh_{os.getpid()}_"
                             f"{time.monotonic_ns()}.addr")
    proc = subprocess.Popen(
        [sys.executable, "-m", "torchft_tpu.lighthouse",
         "--bind", "127.0.0.1:0", "--min-replicas", "2",
         "--join-timeout-ms", "2000", "--quorum-tick-ms", "20",
         "--heartbeat-fresh-ms", "300", "--address-file", addr_file,
         *extra_args],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        if os.path.exists(addr_file):
            with open(addr_file) as f:
                addr = f.read().strip()
            if addr:
                return proc, addr
        if proc.poll() is not None:
            raise RuntimeError("lighthouse subprocess died during startup")
        time.sleep(0.05)
    proc.kill()
    raise RuntimeError("lighthouse subprocess never wrote its address")


def _run_failover_job(lighthouse_addrs, total_steps, on_step,
                      min_replica_size=2):
    """Two replica groups (threads) training an MLP against the given
    lighthouse candidate list. ``on_step(step)`` fires from group 0's loop
    once per step (the kill hook). Returns per-group dicts with params,
    commits trace, and manager metrics."""
    from torchft_tpu import HostCommunicator
    from torchft_tpu.parallel import FTTrainer

    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 8)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32)
    from torchft_tpu.models import MLP

    model = MLP(features=(16,), num_classes=2)

    def loss_fn(params, batch):
        logits = model.apply(params, batch["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()

    params0 = model.init(jax.random.key(7), jnp.zeros((1, 8)))
    results = {}
    errors = {}

    def worker(group: int) -> None:
        trainer = FTTrainer(
            loss_fn=loss_fn, tx=optax.sgd(0.05), params=params0,
            manager_factory=lambda load, save: Manager(
                comm=HostCommunicator(timeout_sec=30),
                load_state_dict=load, state_dict=save,
                min_replica_size=min_replica_size,
                replica_id=f"group{group}",
                lighthouse_addr=lighthouse_addrs, rank=0, world_size=1,
                timeout_ms=30_000, quorum_timeout_ms=30_000),
        )
        try:
            commits = []
            while trainer.manager.current_step() < total_steps:
                batch = {"x": x, "y": y}
                _, committed = trainer.train_step(batch)
                if committed:
                    commits.append((trainer.manager.current_step(),
                                    trainer.manager.quorum_id()))
                if group == 0:
                    on_step(trainer.manager.current_step())
            results[group] = {
                "params": jax.device_get(trainer.params),
                "commits": commits,
                "metrics": trainer.manager.metrics(),
            }
        except Exception as e:  # noqa: BLE001
            errors[group] = e
        finally:
            trainer.shutdown()

    threads = [threading.Thread(target=worker, args=(g,)) for g in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errors, f"group failures: {errors!r}"
    assert set(results) == {0, 1}
    return results


@requires_native
@pytest.mark.integration
@pytest.mark.control_plane
class TestStandbyFailoverMidRun:
    def test_primary_sigkill_mid_run_commits_without_ring_rebuild(
            self, tmp_path):
        """Acceptance: primary SIGKILL mid-run -> managers re-dial the warm
        standby and commit the in-flight step with no ring rebuild
        (reconfigure_count frozen at the initial one), no vote aborts, and
        bitwise-identical final params; the failover is observable as
        lighthouse_redials > 0."""
        from torchft_tpu._native import Lighthouse

        proc, primary_addr = _spawn_lighthouse_subprocess(tmp_path)
        standby = Lighthouse(bind="127.0.0.1:0", min_replicas=2,
                             join_timeout_ms=2000, quorum_tick_ms=20,
                             heartbeat_fresh_ms=300,
                             standby_of=primary_addr, replicate_ms=30)
        killed = threading.Event()
        total_steps, kill_at = 8, 4

        def on_step(step: int) -> None:
            if step >= kill_at and not killed.is_set():
                killed.set()
                proc.send_signal(signal.SIGKILL)
                proc.wait(timeout=10)

        try:
            results = _run_failover_job(
                f"{primary_addr},{standby.address()}", total_steps, on_step)
        finally:
            if proc.poll() is None:
                proc.kill()
            standby.shutdown()
        assert killed.is_set(), "kill hook never fired"

        a, b = results[0], results[1]
        # Bitwise convergence across the failover.
        for la, lb in zip(jax.tree_util.tree_leaves(a["params"]),
                          jax.tree_util.tree_leaves(b["params"])):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        for r in (a, b):
            mx = r["metrics"]
            # Same membership across the failover -> quorum_id constant on
            # every commit -> exactly the initial communicator configure.
            assert len({qid for _, qid in r["commits"]}) == 1
            assert mx["reconfigure_count"] == 1.0
            assert mx["aborted_steps"] == 0.0
            assert [s for s, _ in r["commits"]] == list(
                range(1, total_steps + 1))
        # The failover is observable: at least one group re-dialed.
        assert (a["metrics"]["lighthouse_redials"]
                + b["metrics"]["lighthouse_redials"]) >= 1


@requires_native
@pytest.mark.integration
@pytest.mark.control_plane
@pytest.mark.nightly
@pytest.mark.slow
class TestBlackholeChaosRound:
    def test_chaos_round_with_lighthouse_blackholed(self, tmp_path,
                                                    monkeypatch):
        """Nightly chaos round: transport chaos on the manager/store
        channels while the primary lighthouse is BLACK-HOLED mid-run
        (SIGSTOP: sockets stay open, nothing answers — the death shape
        that refused-connect classification cannot see). Managers must
        time out, re-dial the standby, and finish bitwise identical."""
        from torchft_tpu._native import Lighthouse

        monkeypatch.setenv(
            "TORCHFT_CHAOS",
            "seed=11;manager:latency_ms=1,reset_rate=0.02;"
            "store:reset_rate=0.02")
        proc, primary_addr = _spawn_lighthouse_subprocess(tmp_path)
        standby = Lighthouse(bind="127.0.0.1:0", min_replicas=2,
                             join_timeout_ms=2000, quorum_tick_ms=20,
                             heartbeat_fresh_ms=300,
                             standby_of=primary_addr, replicate_ms=30)
        stopped = threading.Event()
        total_steps, stop_at = 8, 3

        def on_step(step: int) -> None:
            if step >= stop_at and not stopped.is_set():
                stopped.set()
                proc.send_signal(signal.SIGSTOP)

        try:
            results = _run_failover_job(
                f"{primary_addr},{standby.address()}", total_steps, on_step)
        finally:
            try:
                proc.send_signal(signal.SIGCONT)
            except Exception:  # noqa: BLE001
                pass
            proc.kill()
            standby.shutdown()
        assert stopped.is_set()

        a, b = results[0], results[1]
        for la, lb in zip(jax.tree_util.tree_leaves(a["params"]),
                          jax.tree_util.tree_leaves(b["params"])):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        for r in (a, b):
            assert [s for s, _ in r["commits"]][-1] == total_steps
        assert (a["metrics"]["lighthouse_redials"]
                + b["metrics"]["lighthouse_redials"]) >= 1


# -------------------------------------------------- native: latency vs N


@requires_native
@pytest.mark.control_plane
@pytest.mark.slow
@pytest.mark.nightly
class TestQuorumLatencyBench:
    def test_fast_path_beats_slow_path_at_64_clients(self):
        """The acceptance gate for bench.py's quorum_latency_vs_n: at 64
        simulated manager clients with 2ms arrival jitter, steady-state
        fast-path p50 is >= 5x below the slow path's (whose floor is the
        fan-in wait for the last arrival), and fast-path p50 grows
        sublinearly with N (16 -> 64 clients: far less than 4x)."""
        import bench

        r64_fast = bench.bench_quorum_latency_vs_n(n=64, steps=20,
                                                   fast_path=True)
        r64_slow = bench.bench_quorum_latency_vs_n(n=64, steps=20,
                                                   fast_path=False)
        r16_fast = bench.bench_quorum_latency_vs_n(n=16, steps=20,
                                                   fast_path=True)
        assert r64_fast["fast_path_hits"] > 0
        assert r64_slow["fast_path_hits"] == 0
        assert r64_slow["p50_ms"] >= 5 * r64_fast["p50_ms"], (
            r64_slow["p50_ms"], r64_fast["p50_ms"])
        # Sublinear growth in N on the fast path: 4x the clients must cost
        # far less than 4x the p50.
        assert r64_fast["p50_ms"] < 4 * max(r16_fast["p50_ms"], 0.05), (
            r16_fast["p50_ms"], r64_fast["p50_ms"])

    def test_failover_bench_timeline(self):
        import bench

        fo = bench.bench_quorum_failover(n=4, steps=16, kill_at=8)
        assert fo["quorum_id_stable_across_failover"]
        assert fo["redials_total"] >= 1
        assert fo["failover_spike_ms"] > fo["pre_kill_p50_ms"]
