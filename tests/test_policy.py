"""Adaptive fault-tolerance policy tests (ISSUE 10,
docs/design/adaptive_policy.md).

Tier-1 (marker ``policy``, ``scripts/test.sh policy``): the FTPolicy
knob bundle, the PolicyController's hysteresis ladder, the int8 +
error-feedback wire rung (quantizer units, socketpair-ring cross-rank
bitwise identity at worlds 2/3/5, ~1/4 ring bytes, EF drift A/B), the
Manager's commit-boundary switch machinery (refusal mid-heal /
mid-deferred, event stamping, state-dict adoption, fake-store
coordination incl. the switch-racing-a-heal deferral), the
DiLoCoTrainer cadence setter, and the AdaptiveTrainer mode transitions.

The phase-varying adaptive-vs-fixed chaos soak (the acceptance gate)
rides ``nightly``+``slow`` like the other soaks and needs the native
control plane.
"""

import threading
from unittest.mock import MagicMock

import numpy as np
import pytest

import conftest
from torchft_tpu._native import QuorumResult
from torchft_tpu.backends.host import HostCommunicator
from torchft_tpu.communicator import (CommunicatorError, DummyCommunicator,
                                      Int8Wire)
from torchft_tpu.manager import Manager
from torchft_tpu.policy import (LADDER, POLICIES, AdaptiveTrainer,
                                FTPolicy, PolicyController)

pytestmark = pytest.mark.policy


# --------------------------------------------------------------- helpers


def quorum_result(
    quorum_id=1,
    recover_manager_address="manager1:1234",
    store_address="",
    max_step=1,
    max_rank=0,
    max_world_size=2,
    replica_rank=0,
    replica_world_size=2,
    heal=False,
):
    return QuorumResult(
        quorum_id=quorum_id,
        recover_manager_address=recover_manager_address,
        store_address=store_address,
        max_step=max_step,
        max_rank=max_rank,
        max_world_size=max_world_size,
        replica_rank=replica_rank,
        replica_world_size=replica_world_size,
        heal=heal,
    )


def make_manager(client, comm=None, min_replica_size=1, **kwargs):
    return Manager(
        comm=comm or DummyCommunicator(),
        load_state_dict=kwargs.pop("load_state_dict", MagicMock()),
        state_dict=kwargs.pop("state_dict", lambda: {"w": np.ones(2)}),
        min_replica_size=min_replica_size,
        rank=0,
        world_size=1,
        replica_id=kwargs.pop("replica_id", "policytest"),
        _manager_client=client,
        **kwargs,
    )


def boundary(m, tree=None):
    """One scripted step/allreduce/vote boundary; returns the vote."""
    m.step()
    m.allreduce(tree if tree is not None
                else {"g": np.ones(4, np.float32)}).result()
    return m.should_commit()


class FakeStore:
    """Dict-backed stand-in for the native StoreClient (set/get of the
    policy decision + healset keys), injectable via the Manager's
    per-address store-client cache."""

    def __init__(self):
        self.kv = {}
        self.lock = threading.Lock()

    def set(self, key, value):
        with self.lock:
            self.kv[key] = value if isinstance(value, bytes) \
                else str(value).encode()

    def get(self, key, timeout_ms=0):
        with self.lock:
            if key not in self.kv:
                raise KeyError(key)
            return self.kv[key]


# --------------------------------------------------------------- FTPolicy


class TestFTPolicy:
    def test_registry_and_ladder(self):
        assert [p.name for p in LADDER] == [
            "overlap-bf16", "overlap-bf16-ckpt8", "sync-f32",
            "sync-bf16", "sync-int8", "diloco-8"]
        for name in ("sync-f32", "overlap-bf16", "diloco-16",
                     "sync-int8"):
            assert POLICIES[name].name == name

    def test_validation(self):
        with pytest.raises(ValueError, match="overlap_steps"):
            FTPolicy("x", overlap_steps=2)
        with pytest.raises(ValueError, match="wire rung"):
            FTPolicy("x", wire=9)
        with pytest.raises(ValueError, match="sync_every"):
            FTPolicy("x", sync_every=0)
        with pytest.raises(ValueError, match="mutually exclusive"):
            FTPolicy("x", diloco=True, overlap_steps=1)

    def test_state_roundtrip_matches_ladder_names(self):
        for p in LADDER:
            back = FTPolicy.from_state(p.to_state(), ladder=LADDER)
            assert back.knobs() == p.knobs()
            assert back.name == p.name
        # Off-ladder knobs synthesize a descriptive name.
        odd = FTPolicy("custom", wire=2, ckpt_every=3)
        back = FTPolicy.from_state(odd.to_state(), ladder=LADDER)
        assert back.knobs() == odd.knobs()
        assert "int8" in back.name

    def test_wire_dtype_mapping(self):
        import jax.numpy as jnp

        assert POLICIES["sync-f32"].wire_dtype() is None
        assert POLICIES["sync-bf16"].wire_dtype() == jnp.bfloat16
        # int8 transfers D2H in full precision; quantization happens
        # host-side where the EF residual lives.
        assert POLICIES["sync-int8"].wire_dtype() is None


class TestPolicyController:
    def mk(self, **kw):
        kw.setdefault("window", 4)
        kw.setdefault("escalate_failures", 2)
        kw.setdefault("relax_after", 3)
        kw.setdefault("cooldown", 1)
        return PolicyController(**kw)

    def test_escalates_on_windowed_failures(self):
        c = self.mk()
        assert c.note_boundary(False) is None  # 1 failure: under thresh
        prop = c.note_boundary(False)
        assert prop is not None and prop[0] == 1
        assert "escalate" in prop[1]
        # The controller itself does not move until the switch lands.
        assert c.rung == 0
        c.sync_rung(1)
        assert c.rung == 1

    def test_reconfigure_counts_as_failure(self):
        c = self.mk()
        c.note_boundary(True, reconfigured=True)
        prop = c.note_boundary(True, reconfigured=True)
        assert prop is not None and prop[0] == 1

    def test_relaxes_after_quiet_window(self):
        c = self.mk()
        c.sync_rung(2)
        out = [c.note_boundary(True) for _ in range(3)]
        assert out[:2] == [None, None]
        assert out[2] is not None and out[2][0] == 1
        assert "relax" in out[2][1]

    def test_cooldown_and_window_reset_bound_flapping(self):
        c = self.mk(cooldown=3)
        c.note_boundary(False)
        c.note_boundary(False)
        c.sync_rung(1)  # switch landed; window cleared
        # Immediately after a switch, neither old failures nor fresh
        # ones inside the cooldown can move the ladder again.
        assert c.note_boundary(False) is None
        assert c.note_boundary(False) is None
        prop = c.note_boundary(False)  # cooldown satisfied, 3 fresh
        assert prop is not None and prop[0] == 2

    def test_top_rung_saturates_and_bottom_stops_relaxing(self):
        c = self.mk()
        c.sync_rung(len(c.ladder) - 1)
        c.note_boundary(False)
        assert c.note_boundary(False) is None  # nowhere to escalate
        c2 = self.mk()
        for _ in range(6):
            assert c2.note_boundary(True) is None  # already at rung 0

    def test_diloco_rung_gated_on_comm_frac(self):
        c = self.mk(diloco_min_comm_frac=0.5)
        c.sync_rung(len(c.ladder) - 2)  # next rung up is diloco
        c.note_boundary(False, comm_frac=0.01)
        assert c.note_boundary(False, comm_frac=0.01) is None
        c.sync_rung(len(c.ladder) - 2)
        for _ in range(4):  # drive the comm EMA above the gate
            c.note_boundary(True, comm_frac=0.9)
        c.note_boundary(False, comm_frac=0.9)
        prop = c.note_boundary(False, comm_frac=0.9)
        assert prop is not None and c.ladder[prop[0]].diloco

    def test_signals_surface(self):
        c = self.mk()
        c.note_boundary(False, comm_frac=0.4)
        sig = c.last_signals
        assert sig.failures_in_window == 1
        assert sig.failure_rate == 1.0
        assert sig.comm_frac > 0.0
        assert set(sig.as_dict()) == {
            "failures_in_window", "window", "failure_rate",
            "comm_frac", "quiet_boundaries", "churn_rate",
            "fleet_p95_ms", "straggler_score"}
        # Fleet hints flow through note_boundary into the signals
        # (docs/design/fleet_health.md); absent they stay 0.0.
        assert sig.fleet_p95_ms == 0.0
        assert sig.straggler_score == 0.0
        c.note_boundary(True, fleet_p95_ms=1234.5, straggler_score=2.5)
        sig = c.last_signals
        assert sig.fleet_p95_ms == 1234.5
        assert sig.straggler_score == 2.5


# -------------------------------------------------------------- int8 wire


class TestInt8Quantizer:
    def test_roundtrip_error_bounded_per_segment(self):
        rng = np.random.default_rng(3)
        x = (rng.normal(size=200_003) * 10).astype(np.float32)
        w = Int8Wire.quantize(x)
        err = np.abs(w.dequantize(np.float32) - x)
        # Affine with 254 levels: |err| <= scale/2 per element.
        for s in range(len(w.scales)):
            sl = slice(s * w.seg_elems,
                       min((s + 1) * w.seg_elems, x.size))
            assert err[sl].max() <= w.scales[s] / 2 + 1e-6

    def test_non_finite_segment_encodes_zero_and_ef_recovers(self):
        """A loss-spike inf/NaN element must not poison the rung: the
        segment encodes as exact zero (finite reconstruction), and the
        Manager's residual ledger drops the junk step so the NEXT clean
        contribution quantizes normally — unlike banking a NaN residual
        that would re-fold into every later step forever."""
        x = np.linspace(-1, 1, 70_000).astype(np.float32)
        bad = x.copy()
        bad[123] = np.nan  # poisons segment 0 only
        w = Int8Wire.quantize(bad)
        d = w.dequantize(np.float32)
        assert np.isfinite(d).all()
        # The poisoned segment reconstructs to exact zero; the clean
        # segment quantizes normally.
        assert not d[:65_536].any()
        assert abs(d[65_536:] - x[65_536:]).max() <= w.scales[1] / 2 + 1e-6
        # Manager-level recovery: one poisoned step between clean ones.
        from unittest.mock import MagicMock as MM

        client = MM()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = True
        m = make_manager(client, comm=DummyCommunicator(world_size=2),
                         policy=POLICIES["sync-int8"])
        try:
            for step_vals in (x, bad, x, x):
                m.step()
                out = m.allreduce({"g": step_vals.copy()}).result()
                assert np.isfinite(np.asarray(out["g"])).all()
                assert m.should_commit()
            for r in m._ef_residuals.values():
                assert np.isfinite(r).all()
        finally:
            m.shutdown()

    def test_constant_segments_exact(self):
        c = np.full(70_000, -7.5, np.float32)  # spans two segments
        w = Int8Wire.quantize(c)
        np.testing.assert_array_equal(w.dequantize(np.float32), c)
        z = Int8Wire.zeros_like(130_000)
        assert not z.dequantize(np.float32).any()

    def test_bytes_roundtrip_and_quarter_ratio(self):
        rng = np.random.default_rng(4)
        x = rng.normal(size=300_001).astype(np.float32)
        w = Int8Wire.quantize(x)
        raw = w.to_bytes()
        assert len(raw) == Int8Wire.payload_nbytes(x.size)
        assert len(raw) / x.nbytes < 0.26  # ~1/4 of f32 + headers
        back = Int8Wire.from_bytes(raw, x.size)
        np.testing.assert_array_equal(back.dequantize(np.float32),
                                      w.dequantize(np.float32))

    def test_error_feedback_drives_repeated_average_error_to_zero(self):
        """The rung's acceptance numeric: repeatedly quantizing the SAME
        contribution with the residual folded back drives the cumulative
        (and so the mean) reconstruction error to a bounded constant —
        mean error -> 0 as 1/t — while feedback-free quantization
        repeats the identical bias every round (unbounded cumulative
        drift, mean error constant)."""
        rng = np.random.default_rng(5)
        x = rng.normal(size=50_000).astype(np.float32)
        rounds = 40
        res = np.zeros_like(x)
        cum_ef = np.zeros_like(x)
        cum_raw = np.zeros_like(x)
        for _ in range(rounds):
            v = x + res
            w = Int8Wire.quantize(v)
            d = w.dequantize(np.float32)
            res = v - d
            cum_ef += d
            cum_raw += Int8Wire.quantize(x).dequantize(np.float32)
        drift_ef = np.abs(cum_ef - rounds * x).max()
        drift_raw = np.abs(cum_raw - rounds * x).max()
        scale = Int8Wire.quantize(x).scales.max()
        # EF: total drift stays within ~one quantization step forever.
        assert drift_ef <= scale + 1e-5
        # No feedback: the per-round bias accumulates linearly.
        assert drift_raw > 10 * drift_ef
        mean_err = np.abs(cum_ef / rounds - x).max()
        assert mean_err < np.abs(
            Int8Wire.quantize(x).dequantize(np.float32) - x).max()


def _socketpair_rings(world):
    import socket as _socket

    from torchft_tpu.backends.host import _Ring

    pairs = [_socket.socketpair() for _ in range(world)]
    return [_Ring(pairs[r][0], pairs[(r - 1) % world][1],
                  _socket.socket())
            for r in range(world)]


class TestInt8WireRing:
    """The int8+EF rung over real sockets (the same socketpair-ring
    battery as the bf16 wire, tests/test_communicator.py): raw
    contributions, canonical-rank-order folds, cross-rank bitwise
    identity, ~1/4 ring bytes, and reduce-scatter stripe identity."""

    def _run(self, world, fn):
        rings = _socketpair_rings(world)
        comms = []
        for r in range(world):
            c = HostCommunicator(timeout_sec=15)
            c._rank, c._world = r, world
            comms.append(c)
        out = [None] * world
        errors = []

        def w(r):
            try:
                out[r] = fn(comms[r], rings[r], r)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        ts = [threading.Thread(target=w, args=(r,)) for r in range(world)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        alive = [t for t in ts if t.is_alive()]
        for ring in rings:
            ring.close()
        assert not alive, "int8 wire ring deadlocked"
        return out, comms, errors

    @pytest.mark.parametrize("world", [2, 3, 5])
    def test_cross_rank_bitwise_identity(self, world):
        rng = np.random.default_rng(world)
        xs = [rng.normal(size=10_007).astype(np.float32)
              for _ in range(world)]
        ws = [Int8Wire.quantize(x) for x in xs]

        out, comms, errors = self._run(
            world, lambda c, ring, r: c._ring_allreduce_int8(
                ring, Int8Wire.quantize(xs[r]),
                np.dtype(np.float32)))
        assert not errors, errors
        # Canonical rank-order fold of once-quantized contributions.
        expected = np.zeros(10_007, np.float32)
        for w in ws:
            expected += w.dequantize(np.float32)
        for o in out:
            np.testing.assert_array_equal(o, expected)
        for c in comms:
            c.shutdown()

    def test_ring_bytes_quarter_of_f32(self):
        size = 300_001
        rng = np.random.default_rng(9)
        xs = [rng.normal(size=size).astype(np.float32) for _ in range(2)]
        out, comms, errors = self._run(
            2, lambda c, ring, r: c._ring_allreduce_int8(
                ring, Int8Wire.quantize(xs[r]), np.dtype(np.float32)))
        assert not errors, errors
        exact_f32_bytes = 4 * size  # 2(n-1)/n * payload at world 2
        for c in comms:
            sent = c.ring_bytes_total()
            assert sent == Int8Wire.payload_nbytes(size)
            assert sent / exact_f32_bytes < 0.26
            assert c.int8_ring_bytes_total() == sent
            c.shutdown()

    @pytest.mark.parametrize("world", [2, 3])
    def test_reduce_scatter_stripes_bitwise_match_allreduce(self, world):
        from torchft_tpu.communicator import shard_bounds

        rng = np.random.default_rng(11)
        xs = [rng.normal(size=9_001).astype(np.float32)
              for _ in range(world)]

        full, comms, errors = self._run(
            world, lambda c, ring, r: c._ring_allreduce_int8(
                ring, Int8Wire.quantize(xs[r]), np.dtype(np.float32)))
        assert not errors
        for c in comms:
            c.shutdown()
        shards, comms, errors = self._run(
            world, lambda c, ring, r: c._ring_reduce_scatter_int8(
                ring, Int8Wire.quantize(xs[r]), np.dtype(np.float32)))
        assert not errors
        bounds = shard_bounds(9_001, world)
        for r in range(world):
            np.testing.assert_array_equal(
                shards[r], full[0][bounds[r]:bounds[r + 1]])
        for c in comms:
            c.shutdown()

    def test_do_allreduce_wire_mixes_int8_and_exact_chunks(self):
        rng = np.random.default_rng(12)
        xs = [rng.normal(size=2_000).astype(np.float32)
              for _ in range(2)]
        ints = np.arange(9, dtype=np.int64)
        ws = [Int8Wire.quantize(x) for x in xs]

        def fn(c, ring, r):
            return c._do_allreduce_wire(
                ring,
                [Int8Wire.quantize(xs[r]), ints * (r + 1)],
                [np.dtype(np.float32), np.dtype(np.int64)], "sum")

        out, comms, errors = self._run(2, fn)
        assert not errors, errors
        expected = ws[0].dequantize(np.float32) \
            + ws[1].dequantize(np.float32)
        for o in out:
            np.testing.assert_array_equal(o[0], expected)
            np.testing.assert_array_equal(o[1], ints * 3)
        for c in comms:
            c.shutdown()

    def test_payload_tag_skew_detected(self):
        """DiLoCo outer-round pseudo-gradients and per-step gradients
        have identical geometry; the preamble's payload tag is what
        keeps a one-boundary DiLoCo-transition skew from folding one
        into the other."""
        x = np.ones(1_024, np.float32)

        def fn(c, ring, r):
            return c._do_allreduce_wire(
                ring, [x.copy()], [np.dtype(np.float32)], "sum",
                "step" if r == 0 else "diloco")

        out, comms, errors = self._run(2, fn)
        assert len(errors) == 2, (errors, out)
        assert all("wire format skew" in str(e) for e in errors)
        for c in comms:
            c.shutdown()

    def test_wire_format_skew_detected_not_folded(self):
        """The preamble guarantee the adaptive layer leans on: two ranks
        disagreeing on the wire format (one switched to int8, one
        missed the decision) must get a clean CommunicatorError — never
        a silent garbage fold."""
        x = np.ones(4_096, np.float32)

        def fn(c, ring, r):
            bufs = [Int8Wire.quantize(x)] if r == 0 else [x.copy()]
            return c._do_allreduce_wire(
                ring, bufs, [np.dtype(np.float32)], "sum")

        out, comms, errors = self._run(2, fn)
        assert len(errors) == 2, (errors, out)
        for e in errors:
            assert isinstance(e, CommunicatorError)
            assert "wire format skew" in str(e)
        for c in comms:
            c.shutdown()


# ------------------------------------------------------- manager policy


class TestManagerPolicy:
    def test_synthesized_policy_from_legacy_knobs(self):
        import jax.numpy as jnp

        client = MagicMock()
        m = make_manager(client, overlap_steps=1,
                         allreduce_wire_dtype=jnp.bfloat16)
        try:
            p = m.policy()
            assert p.overlap_steps == 1 and p.wire_name() == "bf16"
            assert m.metrics_info()["policy_name"] == p.name
            # Legacy managers stay legacy: no policy fields in the
            # state dict (tests pin its exact shape).
            assert set(m.state_dict()) == {"step", "batches_committed"}
        finally:
            m.shutdown()

    def test_set_policy_applies_knobs_and_stamps_event(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = True
        m = make_manager(client, policy=POLICIES["sync-f32"])
        try:
            assert m.set_policy(POLICIES["sync-int8"], reason="test")
            assert m.policy().name == "sync-int8"
            assert m._wire_dtype is None
            assert m.set_policy(POLICIES["overlap-bf16"])
            assert m.overlap_steps() == 1
            assert str(m._wire_dtype) == "bfloat16"
            mx = m.metrics()
            assert mx["policy_switches_total"] == 2
            assert m.metrics_info()["policy_name"] == "overlap-bf16"
            events = [e for e in m.history()
                      if e.get("event") == "policy_switch"]
            assert [(e["from"], e["to"]) for e in events] == [
                ("sync-f32", "sync-int8"),
                ("sync-int8", "overlap-bf16")]
            assert events[0]["reason"] == "test"
        finally:
            m.shutdown()

    def test_switch_refused_mid_heal_and_mid_deferred(self):
        from concurrent.futures import Future

        client = MagicMock()
        client.quorum.return_value = quorum_result()
        m = make_manager(client, policy=POLICIES["sync-f32"])
        try:
            with m._metrics_lock:
                m._healing = True
            assert not m.set_policy(POLICIES["sync-int8"])
            with m._metrics_lock:
                m._healing = False
            fut: Future = Future()
            m.stage_deferred(fut)
            assert not m.set_policy(POLICIES["sync-int8"])
            mx = m.metrics()
            assert mx["policy_switch_refusals"] == 2
            assert m.metrics_info()["policy_name"] == "sync-f32"
            whys = [e["why"] for e in m.history()
                    if e.get("event") == "policy_switch_refused"]
            assert whys == ["healing", "deferred in flight"]
            fut.set_result({})
            m.drain_deferred()
            assert m.set_policy(POLICIES["sync-int8"])
        finally:
            m.shutdown()

    def test_state_dict_adoption(self):
        client = MagicMock()
        donor = make_manager(client, policy=POLICIES["sync-int8"])
        healer = make_manager(MagicMock(), policy=POLICIES["sync-f32"],
                              replica_id="healer")
        try:
            sd = donor.state_dict()
            assert sd["policy_wire"] == POLICIES["sync-int8"].wire
            healer.load_state_dict(sd)
            assert healer.policy().name == "sync-int8"
            assert any(e.get("event") == "policy_adopt"
                       for e in healer.history())
        finally:
            donor.shutdown()
            healer.shutdown()

    def test_event_history_depth_configurable(self, monkeypatch):
        m = make_manager(MagicMock(), event_history=7)
        try:
            for i in range(30):
                m._log_event(event="x", i=i)
            assert len(m.history()) == 7
        finally:
            m.shutdown()
        monkeypatch.setenv("TORCHFT_EVENT_HISTORY", "11")
        m = make_manager(MagicMock())
        try:
            assert m._history.maxlen == 11
        finally:
            m.shutdown()

    def test_int8_pipeline_with_error_feedback(self):
        """End-to-end through the Manager's host pipeline: under the
        sync-int8 policy the averaged result is the quantized average
        (bounded error), the EF residual is banked (gauge > 0), and the
        running mean of repeated allreduces of the SAME grads converges
        onto the exact mean (the EF property, now manager-level)."""
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = True
        comm = DummyCommunicator(world_size=2)
        m = make_manager(client, comm=comm,
                         policy=POLICIES["sync-int8"])
        rng = np.random.default_rng(21)
        x = {"g": rng.normal(size=30_000).astype(np.float32)}
        try:
            rounds = 20
            acc = np.zeros_like(x["g"])
            for _ in range(rounds):
                m.step()
                out = m.allreduce({"g": x["g"].copy()}).result()
                acc += np.asarray(out["g"])
                assert m.should_commit()
            # Dummy comm sums only this rank; n=2 halves it.
            mean_err = np.abs(acc / rounds - x["g"] / 2).max()
            single = Int8Wire.quantize(x["g"])
            single_err = np.abs(
                single.dequantize(np.float32) - x["g"]).max() / 2
            assert mean_err < single_err / 4
            assert m.metrics()["wire_quant_residual_bytes"] > 0
        finally:
            m.shutdown()

    def test_delayed_optimizer_stage_guard(self):
        import optax

        from torchft_tpu.optim import DelayedOptimizer

        client = MagicMock()
        client.quorum.return_value = quorum_result()
        m = make_manager(client, policy=POLICIES["sync-f32"])
        opt = DelayedOptimizer(m, optax.sgd(0.1))
        try:
            m.step()
            fut = m.allreduce({"g": np.ones(2, np.float32)})
            with pytest.raises(RuntimeError, match="overlap disabled"):
                opt.stage(MagicMock(), fut)
        finally:
            m.shutdown()


class TestPolicyCoordination:
    """The decider/follower protocol over a (fake) quorum store: the
    participating rank 0 publishes, everyone adopts, switches racing a
    heal are deferred and retried."""

    def _pair(self, store, ctl_kwargs=None):
        ctl_kwargs = ctl_kwargs or dict(window=4, escalate_failures=2,
                                        relax_after=3, cooldown=1)
        ms = []
        for rank in range(2):
            client = MagicMock()
            client.quorum.return_value = quorum_result(
                store_address="fake:0", max_rank=rank,
                replica_rank=rank)
            client.should_commit.return_value = True
            m = make_manager(client,
                             comm=DummyCommunicator(world_size=2),
                             replica_id=f"coord{rank}",
                             policy_controller=PolicyController(
                                 **ctl_kwargs))
            m._healset_store = ("fake:0", store)  # inject the fake
            ms.append((m, client))
        return ms

    def test_decider_publishes_and_follower_adopts(self):
        store = FakeStore()
        ms = self._pair(store)
        try:
            for m, c in ms:
                c.should_commit.return_value = False  # storm
            for _ in range(4):
                for m, _c in ms:
                    boundary(m)
            names = [m.policy().name for m, _ in ms]
            assert names[0] == names[1] != "overlap-bf16", names
            assert store.kv["torchft/policy"]
            # The follower adopted via the coordinated read.
            follower_events = [e for e in ms[1][0].history()
                               if e.get("event") == "policy_switch"]
            assert follower_events
            assert "coordinated" in follower_events[0]["reason"]
        finally:
            for m, _ in ms:
                m.shutdown()

    def test_switch_racing_heal_deferred_then_retried(self):
        store = FakeStore()
        ms = self._pair(store)
        (decider, dc), (follower, fc) = ms
        try:
            dc.should_commit.return_value = False
            fc.should_commit.return_value = False
            # Someone in the quorum is healing: max_world < replica_world.
            dc.quorum.return_value = quorum_result(
                store_address="fake:0", max_rank=0, replica_rank=0,
                max_world_size=1, replica_world_size=2)
            for _ in range(4):
                boundary(decider)
            mx = decider.metrics()
            assert mx["policy_switch_deferrals"] >= 1
            assert decider.policy().name == "overlap-bf16"  # unchanged
            assert any(e.get("event") == "policy_switch_deferred"
                       for e in decider.history())
            # Heal finished: the deferred switch lands at the next
            # boundary.
            dc.quorum.return_value = quorum_result(
                store_address="fake:0", max_rank=0, replica_rank=0)
            boundary(decider)
            assert decider.policy().name != "overlap-bf16"
            boundary(follower)
            assert follower.policy().name == decider.policy().name
        finally:
            for m, _ in ms:
                m.shutdown()

    def test_follower_missing_read_catches_up_next_boundary(self):
        store = FakeStore()
        ms = self._pair(store)
        (decider, dc), (follower, fc) = ms
        try:
            dc.should_commit.return_value = False
            for _ in range(4):
                boundary(decider)
            assert decider.policy().name != "overlap-bf16"
            # The follower read nothing so far (its boundaries never
            # ran); its next boundary reads the persistent key and
            # adopts in one hop — the late-join/missed-read repair.
            boundary(follower)
            assert follower.policy().name == decider.policy().name
        finally:
            for m, _ in ms:
                m.shutdown()


class _PairHub:
    """Two-rank rendezvous 'ring': pairs each rank's n-th wire op with
    the peer's n-th, folds the (dequantized) contributions in canonical
    rank order, and resolves both futures with the identical sum —
    exercising the Manager pipelines, int8 quantization, and policy
    lockstep end-to-end without the native store the real ring's
    rendezvous needs."""

    def __init__(self):
        self.lock = threading.Lock()
        self.counts = {}
        self.pending = {}

    def submit(self, rank, buffers, origs):
        from concurrent.futures import Future

        from torchft_tpu.communicator import _upcast_buffers

        fut = Future()
        with self.lock:
            idx = self.counts.get(rank, 0)
            self.counts[rank] = idx + 1
            entry = self.pending.setdefault(idx, {})
            entry[rank] = (list(buffers), [np.dtype(d) for d in origs],
                           fut)
            ready = len(entry) == 2
            if ready:
                del self.pending[idx]
        if ready:
            vals = {r: _upcast_buffers(b, o)
                    for r, (b, o, _f) in entry.items()}
            sums = [vals[0][i] + vals[1][i]
                    for i in range(len(vals[0]))]
            for _r, (_b, origs_r, f) in entry.items():
                f.set_result([np.array(s, dtype=d)
                              for s, d in zip(sums, origs_r)])
        return fut


class _PairComm(DummyCommunicator):
    """Communicator riding a :class:`_PairHub` for its wire ops."""

    def __init__(self, hub, rank):
        super().__init__(rank=rank, world_size=2)
        self._hub = hub

    def allreduce_wire(self, buffers, orig_dtypes, op="sum"):
        return self._hub.submit(self.rank(), buffers, orig_dtypes)


class TestTwoGroupTransitionsLockstep:
    """The transition acceptance oracle, tier-1 spelling: two groups
    run the AdaptiveTrainer through scripted stable -> storm -> stable
    vote outcomes with coordinated controllers over a fake store; the
    policy must escalate through the wire ladder (including a mid-run
    switch into the int8+EF rung) and relax back, with params BITWISE
    lockstep across groups at every boundary."""

    def test_params_lockstep_through_mid_run_switches(self):
        import jax
        import jax.numpy as jnp
        import optax

        # Ladder without the DiLoCo rung: DiLoCo changes the op cadence,
        # which the hub's strict 1-op-per-boundary pairing (deliberately
        # stricter than the real ring) cannot host under one-boundary
        # adoption skew. The real ring detects that skew via the
        # payload tag (test_payload_tag_skew_detected).
        ladder = LADDER[:5]
        store = FakeStore()
        hub = _PairHub()
        script = [True] * 4 + [False] * 12 + [True] * 14
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(8, 6)), jnp.float32)

        def loss_fn(params, batch):
            return ((batch @ params["w"]) ** 2).mean()

        barrier = threading.Barrier(2)
        results = {}
        errors = []

        def run_group(rank):
            calls = {"n": 0}

            def vote(rank=None, step=None, should_commit=None,
                     timeout_ms=None):
                i = min(calls["n"], len(script) - 1)
                calls["n"] += 1
                return script[i]

            client = MagicMock()
            client.quorum.return_value = quorum_result(
                store_address="fake:0", max_rank=rank,
                replica_rank=rank)
            client.should_commit.side_effect = vote
            trainer = AdaptiveTrainer(
                loss_fn=loss_fn, tx=optax.sgd(0.05),
                params={"w": np.full((6, 2), 0.1, np.float32)},
                manager_factory=lambda load, save: Manager(
                    comm=_PairComm(hub, rank), load_state_dict=load,
                    state_dict=save, min_replica_size=1, rank=0,
                    world_size=1, replica_id=f"pair{rank}",
                    _manager_client=client,
                    policy_controller=PolicyController(
                        ladder=ladder, window=4, escalate_failures=2,
                        relax_after=4, cooldown=1)),
                jit=False)
            trainer.manager._healset_store = ("fake:0", store)
            snaps = []
            names = []
            try:
                for _ in range(len(script)):
                    barrier.wait(timeout=60)
                    trainer.train_step(x)
                    snaps.append(jax.device_get(trainer.params))
                    names.append(trainer.manager.policy().name)
                trainer.flush()
                results[rank] = {
                    "snaps": snaps, "names": names,
                    "final": jax.device_get(trainer.params),
                    "metrics": trainer.manager.metrics(),
                    "events": trainer.manager.history(),
                }
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                try:
                    barrier.abort()
                except Exception:  # noqa: BLE001
                    pass
            finally:
                trainer.shutdown()

        ts = [threading.Thread(target=run_group, args=(r,))
              for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert not errors, errors
        assert len(results) == 2

        # Params bitwise lockstep at EVERY boundary, switches included.
        for i, (a, b) in enumerate(zip(results[0]["snaps"],
                                       results[1]["snaps"])):
            jax.tree_util.tree_map(
                lambda u, v: np.testing.assert_array_equal(
                    u, v, err_msg=f"divergence at boundary {i}"),
                a, b)
        # The storm drove the ladder into the int8 rung mid-run...
        assert "sync-int8" in results[0]["names"], results[0]["names"]
        # ...and the quiet tail relaxed back at least one rung.
        reasons = [str(e.get("reason", ""))
                   for e in results[0]["events"]
                   if e.get("event") == "policy_switch"]
        assert any("escalate" in r for r in reasons), reasons
        assert any("relax" in r for r in reasons), reasons
        # Both groups end within the protocol's bounded adoption skew
        # (the follower reads the decider's publication no later than
        # its next boundary — exactly one rung of lag at a cut point).
        rung_of = {p.name: i for i, p in enumerate(ladder)}
        assert abs(rung_of[results[0]["names"][-1]]
                   - rung_of[results[1]["names"][-1]]) <= 1, (
            results[0]["names"][-3:], results[1]["names"][-3:])
        for r in (0, 1):
            assert results[r]["metrics"]["policy_switches_total"] <= 10
        # The int8 rung's residuals actually engaged on both groups.
        assert all(
            any(n == "sync-int8" for n in results[r]["names"])
            for r in (0, 1))


# --------------------------------------------------------- mode switching


class TestAdaptiveTrainerModes:
    def _trainer(self, policy=None, controller=None):
        import optax

        client = MagicMock()
        client.quorum.return_value = quorum_result(
            max_world_size=1, replica_world_size=1)
        client.should_commit.return_value = True

        def loss_fn(params, batch):
            return ((params["w"] - batch) ** 2).sum()

        kwargs = {}
        if policy is not None:
            kwargs["policy"] = policy
        if controller is not None:
            kwargs["policy_controller"] = controller
        trainer = AdaptiveTrainer(
            loss_fn=loss_fn, tx=optax.sgd(0.1),
            params={"w": np.zeros(4, np.float32)},
            manager_factory=lambda load, save: Manager(
                comm=DummyCommunicator(), load_state_dict=load,
                state_dict=save, min_replica_size=1, rank=0,
                world_size=1, replica_id="adaptive",
                _manager_client=client, **kwargs),
            jit=False)
        return trainer, client

    def test_sync_to_diloco_and_back_at_round_boundaries(self):
        import jax.numpy as jnp

        trainer, _client = self._trainer(policy=POLICIES["sync-f32"])
        batch = jnp.ones(4, jnp.float32)
        try:
            assert trainer.mode() == "sync"
            _, committed = trainer.train_step(batch)
            assert committed is True
            assert trainer.committed_batches == 1
            # Switch to DiLoCo between steps (a commit boundary).
            assert trainer.manager.set_policy(POLICIES["diloco-8"])
            trainer.train_step(batch)
            assert trainer.mode() == "diloco"
            # Inner steps: no boundary, no commit.
            for _ in range(POLICIES["diloco-8"].sync_every - 2):
                _, committed = trainer.train_step(batch)
                assert committed is None
            _, committed = trainer.train_step(batch)  # outer round
            assert committed is True
            assert trainer.committed_batches == \
                1 + POLICIES["diloco-8"].sync_every
            # Switch back mid-cycle: lands only at the NEXT outer round
            # (DiLoCo-mode boundaries ARE outer rounds).
            assert trainer.manager.set_policy(POLICIES["sync-f32"])
            _, committed = trainer.train_step(batch)
            assert trainer.mode() == "diloco" and committed is None
            for _ in range(POLICIES["diloco-8"].sync_every - 1):
                trainer.train_step(batch)
            assert trainer.mode() == "sync"
        finally:
            trainer.shutdown()

    def test_overlap_to_sync_discards_prefetched_grads(self):
        import jax.numpy as jnp

        trainer, _client = self._trainer(policy=POLICIES["overlap-bf16"])
        batch = jnp.ones(4, jnp.float32)
        try:
            assert trainer.mode() == "overlap"
            _, committed = trainer.train_step(batch)
            assert committed is None  # first step: nothing settled yet
            _, committed = trainer.train_step(batch)
            assert committed is True  # previous step's deferred vote
            # A switch while a step is staged is refused...
            assert not trainer.manager.set_policy(POLICIES["sync-f32"])
            # ...and the trainer's own boundary (inside the next
            # train_step's settle) is where a controller switch lands;
            # emulate it by flushing then switching.
            trainer.flush()
            assert trainer.manager.set_policy(POLICIES["sync-f32"])
            trainer.train_step(batch)
            assert trainer.mode() == "sync"
            assert not trainer.manager.deferred_pending()
        finally:
            trainer.shutdown()


class TestDiLoCoSetSyncEvery:
    def _trainer(self, cls, **kw):
        import optax

        from torchft_tpu import local_sgd

        client = MagicMock()
        client.quorum.return_value = quorum_result(
            max_world_size=1, replica_world_size=1)
        client.should_commit.return_value = True

        def loss_fn(params, batch):
            return ((params["w"] - batch) ** 2).sum()

        trainer = getattr(local_sgd, cls)(
            loss_fn=loss_fn, inner_tx=optax.sgd(0.1),
            params={"w": np.zeros(2, np.float32)},
            manager_factory=lambda load, save: Manager(
                comm=DummyCommunicator(), load_state_dict=load,
                state_dict=save, min_replica_size=1, rank=0,
                world_size=1, replica_id="diloco",
                _manager_client=client),
            jit=False, **kw)
        return trainer

    def test_applies_at_next_outer_round(self):
        import jax.numpy as jnp

        t = self._trainer("DiLoCoTrainer", sync_every=4)
        batch = jnp.ones(2, jnp.float32)
        try:
            for _ in range(3):
                _, committed = t.train_step(batch)
                assert committed is None
            t.set_sync_every(2)
            assert t.sync_every == 4  # current cycle completes as-is
            _, committed = t.train_step(batch)  # round at step 4
            assert committed is True
            assert t.sync_every == 2  # applied at the round boundary
            _, committed = t.train_step(batch)
            assert committed is None
            _, committed = t.train_step(batch)  # step 6: new cadence
            assert committed is True
        finally:
            t.shutdown()

    def test_validation(self):
        t = self._trainer("DiLoCoTrainer", sync_every=4)
        try:
            with pytest.raises(ValueError, match="sync_every"):
                t.set_sync_every(0)
        finally:
            t.shutdown()

    def test_streaming_validates_fragment_divisibility(self):
        t = self._trainer("StreamingDiLoCoTrainer", sync_every=8,
                          fragments=4)
        try:
            with pytest.raises(ValueError, match="divisible"):
                t.set_sync_every(6)
            t.set_sync_every(12)  # valid; staged
            assert t.sync_every == 8 and t.interval == 2
        finally:
            t.shutdown()


# ------------------------------------------------------------ chaos phase


class TestChaosIntensity:
    def test_intensity_scales_fault_rates(self):
        from torchft_tpu.chaos import ChaosSchedule, EndpointChaos

        def faults_at(intensity):
            s = ChaosSchedule(seed=7, endpoints={
                "ring": EndpointChaos(reset_rate=0.2)},
                intensity=intensity)
            return sum(1 for _ in range(500)
                       if s.decide("ring", "send").fault is not None)

        assert faults_at(0.0) == 0
        lo, hi = faults_at(1.0), faults_at(3.0)
        assert 0 < lo < hi

    def test_set_intensity_live_and_draw_stream_pure(self):
        from torchft_tpu.chaos import ChaosSchedule, EndpointChaos

        cfg = {"ring": EndpointChaos(reset_rate=0.3, jitter_ms=0.0)}
        a = ChaosSchedule(seed=3, endpoints=cfg, intensity=0.0)
        b = ChaosSchedule(seed=3, endpoints=cfg, intensity=0.0)
        for i in range(100):
            if i == 50:
                a.set_intensity(1.0)
                b.set_intensity(1.0)
            a.decide("ring", "send")
            b.decide("ring", "send")
        assert a.trace() == b.trace()
        assert not any(d.fault for d in a.trace()[:50])
        assert any(d.fault for d in a.trace()[50:])

    def test_spec_parses_intensity(self):
        from torchft_tpu.chaos import parse_spec

        s = parse_spec("seed=5;intensity=0.5;ring:reset_rate=0.1")
        assert s.intensity() == 0.5

    def test_phased_chaos_walks_wall_clock(self):
        from torchft_tpu.chaos import ChaosSchedule
        from torchft_tpu.policy import PhasedChaos

        s = ChaosSchedule(seed=1)
        p = PhasedChaos(s, ((0.0, 0.0), (1000.0, 2.0)))
        assert p.total_seconds() == 1000.0
        assert p.tick() == 2.0
        assert s.intensity() == 2.0


# ------------------------------------------------------------- the soak


@pytest.mark.integration
@pytest.mark.slow
@pytest.mark.nightly
@conftest.requires_native()
class TestAdaptiveVsFixedSoak:
    """ISSUE 10's acceptance gate (ROADMAP item 3): under a seeded
    stable -> storm -> stable chaos phase schedule, the adaptive policy
    must beat EVERY fixed policy it can reach on protocol-committed
    batches/sec, with >= 1 escalation and >= 1 relaxation observed and
    a switch count bounded by the regime changes (no flapping) — and
    both groups bitwise lockstep at the end of every leg.

    Metric semantics (see bench_policy_soak): the gate counts
    ``Manager.batches_committed`` — what the commit protocol durably
    agreed on. diloco-16 loses that gate largely by construction
    (16x coarser commit granularity is exactly the trade the metric
    prices); sync-f32 and overlap-bf16 are the legs the storm-phase
    advantage must genuinely beat."""

    def test_adaptive_beats_every_fixed_policy(self):
        import jax

        import bench

        legs = {}
        for policy in ("adaptive", "sync-f32", "overlap-bf16",
                       "diloco-16"):
            legs[policy] = bench.bench_policy_soak(policy=policy)
            groups = list(legs[policy]["groups"].values())
            jax.tree_util.tree_map(
                lambda a, b: np.testing.assert_array_equal(a, b),
                groups[0]["params"], groups[1]["params"])

        ad = legs["adaptive"]
        for fixed in ("sync-f32", "overlap-bf16", "diloco-16"):
            assert ad["committed_batches_per_s"] \
                > legs[fixed]["committed_batches_per_s"], (
                    f"adaptive did not beat {fixed}: "
                    f"{ad['committed_batches_per_s']:.2f} vs "
                    f"{legs[fixed]['committed_batches_per_s']:.2f}")
            assert legs[fixed]["switches"] == 0  # fixed stayed fixed

        events = ad["events"]
        reasons = [str(e.get("reason", "")) for e in events
                   if e.get("event") == "policy_switch"]
        assert any("escalate" in r for r in reasons), events
        assert any("relax" in r for r in reasons), events
        # No flapping: bounded by regime changes x ladder walk, not by
        # fault count.
        assert ad["switches"] <= 12, events
