"""Schema-stability snapshots of the observability surfaces (tier-1).

Every name below is documented behavior: dashboards, the
``/metrics.json`` endpoint, the Prometheus ``/metrics`` exposition, the
``/trace.json`` Chrome-trace export, the pod runbook's diagnosis
recipes, and the bench emitters all read these by name. A refactor that
renames or drops one silently breaks them long after the refactor's own
tests went green — these tests are the tripwire: a key may be ADDED
freely (add it here), but an existing key disappearing fails loudly.

Three frozen surfaces:
* ``Manager.metrics()`` — numeric-only (every value int/float, no
  per-key carve-outs: string diagnostics moved to ``metrics_info()``);
* the Prometheus text exposition rendered from it
  (``torchft_<key>`` samples + one ``torchft_info`` label set);
* the trace-event JSON schema (phases ``B``/``E``/``X`` (+``M``
  metadata), required context tags on every span).
"""

from unittest.mock import MagicMock

import numpy as np
import pytest

from torchft_tpu import DummyCommunicator
from torchft_tpu.manager import Manager
from torchft_tpu import tracing

pytestmark = pytest.mark.obs

# The documented metrics() schema, by subsystem. Append when a PR adds a
# counter; never remove without a deliberate deprecation (and a grep for
# every reader: docs/*, bench.py, dashboards).
DOCUMENTED_KEYS = frozenset([
    # quorum / control plane
    "quorum_count", "quorum_ms_total", "quorum_ms_last",
    "quorum_fast_path_hits", "quorum_slow_path_rounds",
    "quorum_epoch_last", "quorum_ms_p50", "quorum_ms_p95",
    "quorum_ms_max", "lighthouse_redials",
    "reconfigure_count", "reconfigure_ms_total",
    # healing
    "heal_count", "heal_ms_total", "heal_bytes_total",
    "heal_bytes_resumed_total", "heal_donor_failovers",
    "heal_leaf_digest_mismatches", "heal_attempts_total",
    "heal_last_bytes_committed", "heal_last_payload_bytes",
    "heal_striped_donors", "heal_redials_avoided",
    # allreduce pipeline
    "allreduce_count", "allreduce_ms_total",
    "allreduce_fetch_ms_total", "allreduce_fetch_dispatch_ms_total",
    "allreduce_fetch_wait_ms_total", "allreduce_ring_ms_total",
    "allreduce_put_ms_total", "allreduce_wire_bytes_total",
    "allreduce_ring_wire_bytes_total",
    "allreduce_pack_cache_misses", "allreduce_d2h_async_fallbacks",
    # D2H fetch accounting + hierarchical transport legs
    # (docs/design/hier_transport.md)
    "allreduce_d2h_wire_bytes_total",
    "hier_intra_bytes_total", "hier_leader",
    # cross-step overlap engine
    "allreduce_hidden_ms_total", "allreduce_drain_wait_ms_total",
    "allreduce_inflight", "overlap_steps_deferred",
    "overlap_grads_dropped",
    # sharded update
    "reduce_scatter_count", "update_count", "update_ms_total",
    "shard_state_bytes", "shard_state_resets",
    # commit votes
    "commit_count", "commit_ms_total", "committed_steps",
    "aborted_steps",
    # durable checkpoints
    "ckpt_corrupt_quarantined", "ckpt_recover_fallbacks",
    "ckpt_recover_legacy", "ckpt_cold_starts", "ckpt_save_skipped",
    # live publication (serving tier)
    "publish_count", "publish_skipped", "publish_ms_total",
    "publish_last_generation",
    # transport retries
    "retry_count", "retry_ms_total", "retry_giveups",
    # degraded-mode groups (docs/design/degraded_mode.md)
    "degraded_capacity_fraction", "degrade_events_total",
    "restore_events_total",
    # adaptive FT policy (docs/design/adaptive_policy.md)
    "policy_current", "policy_switches_total",
    "policy_switch_refusals", "policy_switch_deferrals",
    "failure_rate", "wire_quant_residual_bytes",
    "allreduce_int8_ring_bytes_total",
    # observability tier (docs/design/observability.md)
    "trace_spans_total", "trace_spans_dropped", "flight_dumps_total",
    # spot-instance churn (docs/design/churn.md)
    "preempt_notices_total", "preempt_drain_deferrals_total",
    "preempt_deadline_expired_total", "graceful_exits_total",
    "prejoin_heals_total", "joins_coalesced_total",
    "reconfigures_per_min",
    # fleet health plane (docs/design/fleet_health.md): the
    # lighthouse's per-requester hint, refreshed every quorum round
    "fleet_p95_ms", "straggler_score", "fleet_groups",
    "slo_breach", "slo_breaches_total",
    # straggler-aware rebalance (docs/design/fleet_rebalance.md): the
    # fraction in force plus commit-boundary adoption accounting —
    # unconditional, like the degraded-mode trio above
    "rebalance_fraction", "rebalance_adoptions_total",
    "rebalance_deferred_total",
    # RAM checkpoint tier (docs/design/memory_tier.md) — the Manager
    # half only; the store/replicator counters merge in when the tier
    # is armed (see test_ram_tier_merges_keys)
    "ram_ckpt_heals_total", "ram_replicate_skipped",
    "ram_replicate_errors_total", "ram_replica_collapses_total",
    # transport substrate (docs/design/transport_substrate.md):
    # per-QoS-class byte volume, scheduler waits (grants that queued
    # behind another class), async-core connection/request totals, and
    # the sendfile fast-path volume — merged unconditionally (the
    # substrate is process-wide, like the jit-cache stats)
    "transport_qos_ring_bytes_total",
    "transport_qos_heal_bytes_total",
    "transport_qos_publication_bytes_total",
    "transport_qos_demotion_bytes_total",
    "transport_qos_waits_total", "transport_conns_total",
    "transport_requests_total", "transport_sendfile_bytes_total",
    # state attestation (docs/design/state_attestation.md): commit-
    # boundary digest accounting, the quarantine latch + ladder
    # counters, and the digest kernel's trace-time tripwire
    "sdc_digests_total", "sdc_digest_ms_total", "sdc_quarantined",
    "sdc_quarantines_total", "sdc_quarantine_clears_total",
    "sdc_reheals_total", "sdc_refusals_total", "sdc_chaos_flips_total",
    "sdc_digest_cache_misses",
])

# Merged into metrics() only while the RAM tier is armed
# (Manager.enable_ram_tier) — same conditional-merge contract as the
# serving keys in test_attached_publisher_merges_serving_keys.
RAM_TIER_KEYS = frozenset([
    # RamCheckpointStore (peer-push acceptance side)
    "ram_ckpt_images", "ram_ckpt_stored_bytes",
    "ram_ckpt_accepts_total", "ram_ckpt_rejects_total",
    "ram_ckpt_evictions_total", "ram_ckpt_losses_total",
    # RamReplicator (push + demotion side)
    "ram_ckpt_replications_total", "ram_ckpt_bytes_replicated_total",
    "ram_ckpt_push_failures_total", "ram_ckpt_peers",
    "ram_demote_errors", "ram_demote_fatal", "ram_demote_stalls",
    "demote_stage_ms_total", "demote_encode_ms", "demote_ram_ms",
    "demote_replicate_ms", "demote_disk_ms", "demote_durable_ms",
])

# Latency-reservoir quantile keys rendered as ONE Prometheus summary
# family (torchft_quorum_ms{quantile="..."} + _sum/_count) instead of
# bare torchft_<key> gauges — tracing.SUMMARY_SPECS. They stay plain
# numeric keys in Manager.metrics() (the JSON surface is unchanged);
# only the text exposition differs. quorum_ms_max keeps its own gauge
# (summaries have no max slot).
SUMMARY_CONSUMED_KEYS = frozenset(["quorum_ms_p50", "quorum_ms_p95"])

# String-valued diagnostics, SPLIT from the numeric dict at the source
# (Manager.metrics_info): the Prometheus /metrics endpoint renders them
# as one torchft_info label set and the numeric invariant below needs
# no per-key carve-outs.
DOCUMENTED_INFO_KEYS = frozenset([
    "policy_name", "policy_last_reason", "ckpt_last_error",
    "flight_last_path", "ring_topology", "straggler_stage",
])

# Span context tags every exported trace event must carry (the fleet
# merger aligns on quorum_id/epoch/step; dashboards group by the rest).
REQUIRED_TRACE_TAGS = frozenset(tracing.CONTEXT_TAGS)


def make_manager():
    return Manager(
        comm=DummyCommunicator(),
        load_state_dict=MagicMock(),
        state_dict=lambda: {"w": np.ones(2)},
        min_replica_size=2,
        rank=0,
        world_size=1,
        replica_id="metrics-schema",
        _manager_client=MagicMock(),
    )


class TestMetricsSchema:
    def test_every_documented_key_present(self):
        m = make_manager()
        try:
            got = set(m.metrics())
            missing = DOCUMENTED_KEYS - got
            assert not missing, (
                f"Manager.metrics() lost documented counter key(s): "
                f"{sorted(missing)} — dashboards/runbook/bench readers "
                "depend on these by name. If this is a deliberate "
                "rename, update every reader AND this snapshot.")
        finally:
            m.shutdown()

    def test_all_values_are_numeric(self):
        """EVERY metrics() value must be JSON-safe numeric — not just
        the documented set, and with no per-key carve-outs: string
        diagnostics live in metrics_info(), and the Prometheus
        exposition renders metrics() samples unconditionally."""
        m = make_manager()
        try:
            for key, val in m.metrics().items():
                assert isinstance(val, (int, float)) and \
                    not isinstance(val, bool), (
                        f"{key} is {type(val).__name__}, expected "
                        "int/float — string diagnostics belong in "
                        "metrics_info()")
        finally:
            m.shutdown()

    def test_info_split_from_numeric(self):
        """metrics_info() carries the documented string diagnostics —
        all str — and none of them leak back into metrics()."""
        m = make_manager()
        try:
            info = m.metrics_info()
            missing = DOCUMENTED_INFO_KEYS - set(info)
            assert not missing, sorted(missing)
            for key, val in info.items():
                assert isinstance(val, str), key
            assert info["policy_name"]
            overlap = DOCUMENTED_INFO_KEYS & set(m.metrics())
            assert not overlap, (
                f"string diagnostic key(s) {sorted(overlap)} leaked "
                "into the numeric metrics() dict")
        finally:
            m.shutdown()

    def test_attached_publisher_merges_serving_keys(self):
        """Attaching a WeightPublisher via publish() must surface the
        serving tier's counters in the same snapshot."""
        from torchft_tpu.serving import WeightPublisher

        m = make_manager()
        try:
            pub = WeightPublisher()
            gen = m.publish(pub)
            assert gen == 1
            mx = m.metrics()
            for key in ("publish_generations", "publish_delta_ratio_last",
                        "publish_payload_bytes_last", "serve_requests",
                        "serve_bytes_sent", "publish_generation_last",
                        "publish_step_last",
                        # quantized delta publication (ISSUE 20)
                        "publish_delta_leaves_last",
                        "publish_delta_fallback_leaves_last",
                        "publish_delta_wire_bytes_last",
                        "publish_delta_encode_ms_total",
                        "publish_delta_sets",
                        "serve_delta_requests", "serve_delta_bytes_sent",
                        # self-organizing relay tier
                        "relay_beats", "relay_steers", "relays_live",
                        "relay_children_total", "relay_lag_gens_max",
                        "serve_children"):
                assert key in mx, key
            assert mx["publish_count"] == 1
            assert mx["publish_last_generation"] == 1
        finally:
            m.shutdown()

    def test_ram_tier_merges_keys(self):
        """Arming the RAM checkpoint tier must surface the store and
        replicator counters in the same metrics() snapshot — and they
        must be absent while the tier is off (the Manager half of the
        schema stays unconditional either way)."""
        m = make_manager()
        try:
            off = set(m.metrics())
            leaked = RAM_TIER_KEYS & off
            assert not leaked, (
                f"RAM-tier key(s) {sorted(leaked)} present with the "
                "tier disarmed — these are documented as merge-on-arm")
            m.enable_ram_tier(peers=1)
            mx = m.metrics()
            missing = RAM_TIER_KEYS - set(mx)
            assert not missing, sorted(missing)
            for key in RAM_TIER_KEYS:
                val = mx[key]
                assert isinstance(val, (int, float)) and \
                    not isinstance(val, bool), key
        finally:
            m.shutdown()


class TestPrometheusExposition:
    """Freeze the /metrics exposition names: every documented counter
    renders as torchft_<key> with the repo's counter/gauge typing rule,
    and the string diagnostics render as ONE torchft_info sample."""

    def test_documented_names_render(self):
        m = make_manager()
        try:
            text = tracing.prometheus_text(
                m.metrics(), m.metrics_info(),
                labels={"replica_id": m.replica_id()})
        finally:
            m.shutdown()
        for key in DOCUMENTED_KEYS - SUMMARY_CONSUMED_KEYS:
            assert f"torchft_{key}{{" in text, (
                f"/metrics lost sample torchft_{key}")
        assert 'torchft_info{' in text
        for key in DOCUMENTED_INFO_KEYS:
            assert f'{key}="' in text, (
                f"torchft_info lost label {key}")
        assert 'replica_id="metrics-schema"' in text
        # The reservoir quantiles render as ONE summary family now.
        assert "# TYPE torchft_quorum_ms summary" in text
        assert 'quantile="0.5"' in text and 'quantile="0.95"' in text
        assert "torchft_quorum_ms_sum{" in text
        assert "torchft_quorum_ms_count{" in text
        # ...while the exact max stays its own gauge, and the bare
        # quantile gauges are GONE (consumed, not duplicated).
        assert "torchft_quorum_ms_max{" in text
        assert "torchft_quorum_ms_p50{" not in text
        assert "torchft_quorum_ms_p95{" not in text

    def test_counter_vs_gauge_rule(self):
        text = tracing.prometheus_text(
            {"x_total": 1, "y_count": 2.0, "z_ms_last": 3.0})
        assert "# TYPE torchft_x_total counter" in text
        assert "# TYPE torchft_y_count counter" in text
        assert "# TYPE torchft_z_ms_last gauge" in text

    def test_help_and_type_on_every_family(self):
        """Prometheus exposition-format conformance: every sample line
        belongs to a family that was preceded by # HELP and # TYPE
        lines (scrapers surface HELP text; some strict parsers reject
        TYPE-less families)."""
        m = make_manager()
        try:
            text = tracing.prometheus_text(
                m.metrics(), m.metrics_info(),
                labels={"replica_id": m.replica_id()})
        finally:
            m.shutdown()
        helped, typed = set(), set()
        for line in text.splitlines():
            if line.startswith("# HELP "):
                helped.add(line.split()[2])
            elif line.startswith("# TYPE "):
                typed.add(line.split()[2])
            elif line and not line.startswith("#"):
                name = line.split("{", 1)[0].split(" ", 1)[0]
                base = name
                # summary sub-samples belong to the base family
                for suffix in ("_sum", "_count"):
                    if name.endswith(suffix) and \
                            name[: -len(suffix)] in typed:
                        base = name[: -len(suffix)]
                assert base in typed, f"{name} has no # TYPE"
                assert base in helped, f"{name} has no # HELP"

    def test_summary_quantile_values_match_metrics(self):
        """The summary's quantile samples carry the reservoir's p50/p95
        values verbatim — renamed, not recomputed."""
        text = tracing.prometheus_text(
            {"quorum_ms_p50": 12.5, "quorum_ms_p95": 99.25,
             "quorum_ms_total": 250.0, "quorum_count": 20})
        assert 'torchft_quorum_ms{quantile="0.5"} 12.5' in text
        assert 'torchft_quorum_ms{quantile="0.95"} 99.25' in text
        assert "torchft_quorum_ms_sum 250.0" in text
        assert "torchft_quorum_ms_count 20.0" in text

    def test_large_counters_keep_full_precision(self):
        """A %g-style 6-sig-digit render freezes counters past 1e6
        (1000000 and 1000001 both print '1e+06'), zeroing Prometheus
        rate() exactly where byte counters live — values must render
        with full float precision."""
        a = tracing.prometheus_text({"x_total": 1_000_000.0})
        b = tracing.prometheus_text({"x_total": 1_000_001.0})
        assert a != b
        assert "1000001" in b

    def test_label_escaping(self):
        text = tracing.prometheus_text(
            {"a": 1}, {"weird": 'x"y\\z\n'}, labels={"replica_id": "r"})
        assert 'weird="x\\"y\\\\z\\n"' in text


class TestFleetExpositionSchema:
    """Freeze the fleet-side /fleet/metrics names the rebalance plane
    added (docs/design/fleet_rebalance.md): the aggregate gauges and the
    per-group fraction gauge — mirrored family-for-family by the C++
    lighthouse's fleet_metrics_text, so a rename here silently forks the
    two expositions."""

    def test_rebalance_families_render(self):
        from torchft_tpu import fleet

        agg = fleet.FleetAggregator()
        agg.ingest(fleet.StepDigest(replica_id="g0", step=1,
                                    step_wall_ms=100.0))
        text = fleet.status_prometheus(agg.aggregate())
        for family, typ in (
                ("torchft_fleet_rebalance_groups", "gauge"),
                ("torchft_fleet_rebalance_seq", "counter"),
                ("torchft_fleet_rebalance_fraction", "gauge")):
            assert f"# TYPE {family} {typ}" in text, family
        assert 'torchft_fleet_rebalance_fraction{replica_id="g0"} 1.0' \
            in text


class TestTraceEventSchema:
    """Freeze the /trace.json schema: Chrome trace-event JSON whose
    span phases are X (complete) and B/E (still-open at export), plus M
    metadata naming the process and one track per stage; every span
    carries the alignment/context tags."""

    def test_phases_and_required_tags(self):
        tr = tracing.Tracer(steps=4, enabled=True)
        tr.set_context(replica_id="g0", quorum_id=3, epoch=7, step=11,
                       policy_name="sync-f32")
        with tr.span("quorum", fast=True):
            pass
        with tr.span("vote", decision=True):
            pass
        open_span = tr.span("ring", kind="allreduce_wire")  # stays open
        trace = tr.chrome_trace()
        events = trace["traceEvents"]
        assert events, "empty trace"
        phases = {ev["ph"] for ev in events}
        assert phases <= {"X", "B", "E", "M"}, phases
        assert "X" in phases and "B" in phases and "E" in phases
        spans = [ev for ev in events if ev["ph"] in ("X", "B")]
        for ev in spans:
            missing = REQUIRED_TRACE_TAGS - set(ev["args"])
            assert not missing, (ev["name"], sorted(missing))
            assert ev["args"]["step"] == 11
            assert ev["args"]["quorum_id"] == 3
            assert ev["args"]["epoch"] == 7
        # One track per stage: distinct stages -> distinct tids, named
        # by thread_name metadata.
        tid_of = {ev["name"]: ev["tid"] for ev in spans}
        assert len(set(tid_of.values())) == len(tid_of)
        named = {ev["args"]["name"] for ev in events
                 if ev["ph"] == "M" and ev["name"] == "thread_name"}
        assert named == set(tid_of)
        proc = [ev for ev in events
                if ev["ph"] == "M" and ev["name"] == "process_name"]
        assert proc and proc[0]["args"]["name"] == "g0"
        open_span.__exit__(None, None, None)

    def test_open_spans_marked(self):
        tr = tracing.Tracer(steps=4, enabled=True)
        sp = tr.span("heal", donor="d:1")
        trace = tr.chrome_trace()
        begins = [ev for ev in trace["traceEvents"] if ev["ph"] == "B"]
        assert len(begins) == 1
        assert begins[0]["args"]["open"] is True
        sp.__exit__(None, None, None)
