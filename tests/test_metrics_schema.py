"""Schema-stability snapshot of ``Manager.metrics()`` (tier-1).

Every counter below is documented behavior: dashboards, the
``/metrics.json`` endpoint, the pod runbook's diagnosis recipes, and the
bench emitters all read these keys by name. A refactor that renames or
drops one silently breaks them long after the refactor's own tests went
green — this test is the tripwire: a key may be ADDED freely (add it
here), but an existing key disappearing fails loudly.
"""

from unittest.mock import MagicMock

import numpy as np

from torchft_tpu import DummyCommunicator
from torchft_tpu.manager import Manager

# The documented metrics() schema, by subsystem. Append when a PR adds a
# counter; never remove without a deliberate deprecation (and a grep for
# every reader: docs/*, bench.py, dashboards).
DOCUMENTED_KEYS = frozenset([
    # quorum / control plane
    "quorum_count", "quorum_ms_total", "quorum_ms_last",
    "quorum_fast_path_hits", "quorum_slow_path_rounds",
    "quorum_epoch_last", "quorum_ms_p50", "quorum_ms_p95",
    "quorum_ms_max", "lighthouse_redials",
    "reconfigure_count", "reconfigure_ms_total",
    # healing
    "heal_count", "heal_ms_total", "heal_bytes_total",
    "heal_bytes_resumed_total", "heal_donor_failovers",
    "heal_leaf_digest_mismatches", "heal_attempts_total",
    "heal_last_bytes_committed", "heal_last_payload_bytes",
    "heal_striped_donors", "heal_redials_avoided",
    # allreduce pipeline
    "allreduce_count", "allreduce_ms_total",
    "allreduce_fetch_ms_total", "allreduce_fetch_dispatch_ms_total",
    "allreduce_fetch_wait_ms_total", "allreduce_ring_ms_total",
    "allreduce_put_ms_total", "allreduce_wire_bytes_total",
    "allreduce_ring_wire_bytes_total",
    "allreduce_pack_cache_misses", "allreduce_d2h_async_fallbacks",
    # cross-step overlap engine
    "allreduce_hidden_ms_total", "allreduce_drain_wait_ms_total",
    "allreduce_inflight", "overlap_steps_deferred",
    "overlap_grads_dropped",
    # sharded update
    "reduce_scatter_count", "update_count", "update_ms_total",
    "shard_state_bytes", "shard_state_resets",
    # commit votes
    "commit_count", "commit_ms_total", "committed_steps",
    "aborted_steps",
    # durable checkpoints
    "ckpt_corrupt_quarantined", "ckpt_recover_fallbacks",
    "ckpt_recover_legacy", "ckpt_cold_starts", "ckpt_save_skipped",
    # live publication (serving tier)
    "publish_count", "publish_skipped", "publish_ms_total",
    "publish_last_generation",
    # transport retries
    "retry_count", "retry_ms_total", "retry_giveups",
    # adaptive FT policy (docs/design/adaptive_policy.md)
    "policy_current", "policy_switches_total",
    "policy_switch_refusals", "policy_switch_deferrals",
    "failure_rate", "wire_quant_residual_bytes",
    "allreduce_int8_ring_bytes_total",
])

# String-valued diagnostics (like ckpt_last_error): present in every
# snapshot but outside the numeric schema above.
DOCUMENTED_STRING_KEYS = frozenset([
    "policy_name", "policy_last_reason",
])


def make_manager():
    return Manager(
        comm=DummyCommunicator(),
        load_state_dict=MagicMock(),
        state_dict=lambda: {"w": np.ones(2)},
        min_replica_size=2,
        rank=0,
        world_size=1,
        replica_id="metrics-schema",
        _manager_client=MagicMock(),
    )


class TestMetricsSchema:
    def test_every_documented_key_present(self):
        m = make_manager()
        try:
            got = set(m.metrics())
            missing = DOCUMENTED_KEYS - got
            assert not missing, (
                f"Manager.metrics() lost documented counter key(s): "
                f"{sorted(missing)} — dashboards/runbook/bench readers "
                "depend on these by name. If this is a deliberate "
                "rename, update every reader AND this snapshot.")
        finally:
            m.shutdown()

    def test_values_are_numeric(self):
        """Every documented key must stay JSON-safe numeric — the
        /metrics.json contract (string-valued diagnostics like
        ckpt_last_error use their own keys, outside this set)."""
        m = make_manager()
        try:
            mx = m.metrics()
            for key in DOCUMENTED_KEYS:
                assert isinstance(mx[key], (int, float)), (
                    f"{key} is {type(mx[key]).__name__}, expected "
                    "int/float")
        finally:
            m.shutdown()

    def test_string_diagnostics_present(self):
        """The policy identity keys are strings by design (dashboards
        show the policy NAME next to its counters); they must stay
        present and non-numeric-schema."""
        m = make_manager()
        try:
            mx = m.metrics()
            for key in DOCUMENTED_STRING_KEYS:
                assert isinstance(mx[key], str), key
            assert mx["policy_name"]
        finally:
            m.shutdown()

    def test_attached_publisher_merges_serving_keys(self):
        """Attaching a WeightPublisher via publish() must surface the
        serving tier's counters in the same snapshot."""
        from torchft_tpu.serving import WeightPublisher

        m = make_manager()
        try:
            pub = WeightPublisher()
            gen = m.publish(pub)
            assert gen == 1
            mx = m.metrics()
            for key in ("publish_generations", "publish_delta_ratio_last",
                        "publish_payload_bytes_last", "serve_requests",
                        "serve_bytes_sent", "publish_generation_last",
                        "publish_step_last"):
                assert key in mx, key
            assert mx["publish_count"] == 1
            assert mx["publish_last_generation"] == 1
        finally:
            m.shutdown()
