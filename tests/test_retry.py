"""Unit tests for the unified retry/backoff/deadline policy layer
(:mod:`torchft_tpu.retry`) and its integration with the native clients'
call_seq idempotency under injected mid-RPC resets."""

import random
import threading

import pytest

from torchft_tpu import chaos
from torchft_tpu.retry import (RetryError, RetryPolicy, RetryStats,
                               call_with_retry, is_transient)


import conftest

requires_native = conftest.requires_native()


class TestBackoffMath:
    def test_exponential_growth_without_jitter(self):
        p = RetryPolicy(base_delay_ms=10, multiplier=2.0, jitter=0.0,
                        max_delay_ms=1000)
        assert [p.delay_ms(k) for k in range(4)] == [10, 20, 40, 80]

    def test_max_delay_caps_growth(self):
        p = RetryPolicy(base_delay_ms=10, multiplier=10.0, jitter=0.0,
                        max_delay_ms=50)
        assert p.delay_ms(0) == 10
        assert p.delay_ms(5) == 50

    def test_jitter_bounds_and_determinism(self):
        p = RetryPolicy(base_delay_ms=100, multiplier=1.0, jitter=0.5)
        rng = random.Random(7)
        draws = [p.delay_ms(0, rng) for _ in range(200)]
        assert all(50 <= d <= 150 for d in draws)
        assert len(set(draws)) > 1  # actually jittered
        # Seeded rng → reproducible backoff sequence.
        rng2 = random.Random(7)
        assert draws == [p.delay_ms(0, rng2) for _ in range(200)]

    def test_negative_attempt_rejected(self):
        with pytest.raises(ValueError):
            RetryPolicy().delay_ms(-1)


class TestClassification:
    @pytest.mark.parametrize("exc", [
        ConnectionResetError("Connection reset by peer"),
        ConnectionRefusedError("connection refused"),
        BrokenPipeError("broken pipe"),
        TimeoutError("timed out"),
        RuntimeError("transport: send failed"),
        RuntimeError("peer closed connection"),
        RuntimeError("ring send failed: [Errno 104] reset by peer"),
        ValueError("truncated checkpoint stream"),
    ])
    def test_transient(self, exc):
        assert is_transient(exc)

    def test_serve_window_closed_503_is_transient(self):
        """The donor's 503 while its serve window is shut at commit is
        transient BY CONSTRUCTION (the window reopens at the donor's
        next step start) — it must retry with backoff, not surface as a
        failed heal alongside real refusals."""
        import urllib.error

        def http_error(code, msg):
            return urllib.error.HTTPError(
                "http://donor/checkpoint/5", code, msg, None, None)

        assert is_transient(http_error(503, "serve window closed (commit)"))
        # heal-specific classifier agrees
        from torchft_tpu.checkpointing import _heal_transient
        assert _heal_transient(http_error(503,
                                          "serve window closed (commit)"))
        # ...but shutdown, auth and step refusals stay fatal
        assert not is_transient(http_error(503, "shutting down"))
        assert not _heal_transient(http_error(503, "shutting down"))
        assert not is_transient(
            http_error(400, "invalid checkpoint requested: serving 5 "
                            "but got 3"))
        assert not _heal_transient(
            http_error(400, "invalid checkpoint requested: serving 5 "
                            "but got 3"))
        assert not _heal_transient(http_error(401,
                                              "missing/bad bearer token"))

    def test_heal_corrupt_vs_digest_classification(self):
        from torchft_tpu.checkpointing import (HealCorruptError,
                                               LeafDigestError,
                                               _heal_transient)

        # in-transit corruption: re-fetch fixes it
        assert _heal_transient(LeafDigestError("2 leaves failed digest "
                                               "verification"))
        # donor-side corruption: retrying the same donor cannot help
        assert not _heal_transient(HealCorruptError(
            "leaf 'w' failed digest verification 3 times"))

    @pytest.mark.parametrize("exc", [
        RuntimeError("store get timeout waiting for key: foo/bar"),
        RuntimeError("invalid checkpoint requested: serving 5 but got 3"),
        RuntimeError("manager shutting down"),
        RuntimeError("401 unauthorized"),
        PermissionError("auth token mismatch"),
        KeyError("step"),
        ValueError("not a torchft_tpu pytree checkpoint"),
    ])
    def test_fatal(self, exc):
        assert not is_transient(exc)


class TestCallWithRetry:
    def test_retries_transient_then_succeeds(self):
        calls = [0]

        def flaky():
            calls[0] += 1
            if calls[0] < 3:
                raise ConnectionResetError("reset by peer")
            return "ok"

        stats = RetryStats()
        out = call_with_retry(flaky, RetryPolicy(max_attempts=3),
                              stats=stats, sleep=lambda s: None)
        assert out == "ok" and calls[0] == 3
        snap = stats.snapshot()
        assert snap["retry_count"] == 2 and snap["retry_giveups"] == 0

    def test_fatal_error_never_retries(self):
        calls = [0]

        def fatal():
            calls[0] += 1
            raise RuntimeError("auth token mismatch")

        with pytest.raises(RuntimeError, match="auth"):
            call_with_retry(fatal, RetryPolicy(max_attempts=5),
                            sleep=lambda s: None)
        assert calls[0] == 1

    def test_last_attempt_error_propagates_unchanged(self):
        err = ConnectionResetError("reset by peer")

        def always():
            raise err

        stats = RetryStats()
        with pytest.raises(ConnectionResetError) as ei:
            call_with_retry(always, RetryPolicy(max_attempts=3),
                            stats=stats, sleep=lambda s: None)
        assert ei.value is err
        assert stats.snapshot()["retry_giveups"] == 1

    def test_max_attempts_one_disables_retry(self):
        calls = [0]

        def flaky():
            calls[0] += 1
            raise ConnectionResetError("reset")

        with pytest.raises(ConnectionResetError):
            call_with_retry(flaky, RetryPolicy(max_attempts=1),
                            sleep=lambda s: None)
        assert calls[0] == 1

    def test_overall_deadline_stops_retrying(self):
        # Backoff of ~1s/attempt against a 1ms overall deadline: the loop
        # must give up with RetryError instead of sleeping past it.
        def always():
            raise ConnectionResetError("reset")

        stats = RetryStats()
        with pytest.raises(RetryError, match="deadline"):
            call_with_retry(
                always,
                RetryPolicy(max_attempts=10, base_delay_ms=1000,
                            jitter=0.0, overall_deadline_ms=1.0),
                stats=stats, sleep=lambda s: None)
        assert stats.snapshot()["retry_giveups"] == 1

    def test_reconnect_runs_between_attempts(self):
        seen = []

        def flaky():
            if len(seen) == 0:
                raise ConnectionResetError("reset")
            return "ok"

        out = call_with_retry(flaky, RetryPolicy(max_attempts=2),
                              reconnect=lambda: seen.append("reconnect"),
                              sleep=lambda s: None)
        assert out == "ok" and seen == ["reconnect"]

    def test_reconnect_failure_counts_as_attempt(self):
        def flaky():
            raise ConnectionResetError("reset")

        def bad_reconnect():
            raise ConnectionRefusedError("connection refused")

        with pytest.raises(ConnectionRefusedError):
            call_with_retry(flaky, RetryPolicy(max_attempts=2),
                            reconnect=bad_reconnect, sleep=lambda s: None)

    def test_stats_shared_across_threads(self):
        stats = RetryStats()

        def flaky_once():
            # one retry per call via a mutable cell
            cell = [0]

            def f():
                cell[0] += 1
                if cell[0] == 1:
                    raise ConnectionResetError("reset")
                return True

            return call_with_retry(f, RetryPolicy(max_attempts=2),
                                   stats=stats, sleep=lambda s: None)

        threads = [threading.Thread(target=flaky_once) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert stats.snapshot()["retry_count"] == 8


class _ScriptedSchedule(chaos.ChaosSchedule):
    """Deterministic decision script: fault kinds consumed in order per
    channel, then clean. Used to land a fault on an exact call."""

    def __init__(self, script):
        super().__init__(seed=0, endpoints={})
        self._script = dict(script)  # channel -> list of (fault, phase)

    def config_for(self, endpoint):  # every endpoint is "configured"
        return chaos.EndpointChaos()

    def decide(self, endpoint, op):
        channel = endpoint.split(":", 1)[0]
        queue = self._script.get(channel, [])
        fault, phase = queue.pop(0) if queue else (None, "pre")
        d = chaos.Decision(endpoint=endpoint, op=op, n=0, delay_ms=0.0,
                           fault=fault, phase=phase, frac=0.5,
                           blackhole_ms=0.0)
        with self._lock:
            self._trace.append(d)
        return d


@requires_native
class TestNativeClientRetryIdempotency:
    """Injected mid-RPC resets against real native servers: the retry
    layer must absorb them, and the server's call_seq idempotency must
    keep replays safe (no double-set, no wedged quorum/commit round)."""

    def test_store_set_get_survive_post_reset(self):
        from torchft_tpu._native import Store, StoreClient

        store = Store(bind="127.0.0.1:0")
        try:
            # Response "lost" after the server executed each RPC: the
            # retry replays; set is idempotent, get is read-only.
            chaos.install(_ScriptedSchedule({
                "store": [("reset", "post"), ("reset", "post")]}))
            stats = RetryStats()
            c = StoreClient(store.address(), retry_stats=stats,
                            retry_policy=RetryPolicy(
                                max_attempts=3, base_delay_ms=1))
            c.set("k", b"v")     # post-reset on the set → retried replay
            assert c.get("k", timeout_ms=2000) == b"v"  # post-reset too
            assert stats.snapshot()["retry_count"] == 2
        finally:
            chaos.uninstall()
            store.shutdown()

    def test_store_pre_reset_request_never_sent(self):
        from torchft_tpu._native import Store, StoreClient

        store = Store(bind="127.0.0.1:0")
        try:
            chaos.install(_ScriptedSchedule({
                "store": [("reset", "pre")]}))
            stats = RetryStats()
            c = StoreClient(store.address(), retry_stats=stats,
                            retry_policy=RetryPolicy(
                                max_attempts=2, base_delay_ms=1))
            c.set("k2", b"v2")
            assert c.get("k2", timeout_ms=2000) == b"v2"
            assert stats.snapshot()["retry_count"] == 1
        finally:
            chaos.uninstall()
            store.shutdown()

    def test_quorum_and_commit_survive_mid_rpc_reset(self):
        from torchft_tpu._native import (Lighthouse, ManagerClient,
                                         ManagerServer)

        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1,
                        join_timeout_ms=200, quorum_tick_ms=50)
        srv = ManagerServer(replica_id="retrytest",
                            lighthouse_addr=lh.address(),
                            bind="127.0.0.1:0", world_size=1)
        try:
            chaos.install(_ScriptedSchedule({
                "manager": [
                    (None, "pre"),        # connect: clean
                    ("reset", "post"),    # quorum #1: response lost
                    (None, "pre"),        # quorum retry: clean
                    ("reset", "post"),    # should_commit #1: response lost
                    (None, "pre"),        # should_commit retry: clean
                ]}))
            stats = RetryStats()
            c = ManagerClient(srv.address(), retry_stats=stats,
                              retry_policy=RetryPolicy(
                                  max_attempts=3, base_delay_ms=1))
            q = c.quorum(rank=0, step=1,
                         checkpoint_server_addr="http://127.0.0.1:1/x",
                         timeout_ms=10_000)
            # The retried quorum (higher call_seq at a done round) ran a
            # fresh lighthouse round and still yields a valid view.
            assert q.quorum_id > 0
            assert q.replica_world_size == 1
            decided = c.should_commit(rank=0, step=1, should_commit=True,
                                      timeout_ms=10_000)
            assert decided is True
            assert stats.snapshot()["retry_count"] == 2
        finally:
            chaos.uninstall()
            srv.shutdown()
            lh.shutdown()

class TestManagerRetryMetrics:
    def test_manager_metrics_surface_retry_counters(self):
        # Native-independent: the Manager (mocked client) merges its
        # shared RetryStats into metrics(), which _publish_status ships
        # verbatim to the manager's GET /metrics.json.
        from unittest.mock import MagicMock

        from torchft_tpu.communicator import DummyCommunicator
        from torchft_tpu.manager import Manager

        m = Manager(
            comm=DummyCommunicator(),
            load_state_dict=MagicMock(),
            state_dict=lambda: {},
            min_replica_size=1,
            rank=0, world_size=1, replica_id="mx",
            _manager_client=MagicMock(),
        )
        try:
            m._retry_stats.record_retry(3.0)
            mx = m.metrics()
            assert mx["retry_count"] == 1.0
            assert mx["retry_ms_total"] >= 3.0
            assert mx["retry_giveups"] == 0.0
        finally:
            m.shutdown()
