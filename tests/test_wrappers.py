"""Optimizer/data wrapper tests (reference optim_test.py / ddp_test.py
shape: real wrapper, mocked manager)."""

from unittest.mock import MagicMock

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchft_tpu.data import BatchIterator, DistributedSampler
from torchft_tpu.optim import FTOptimizer, OptimizerWrapper


class _Holder:
    def __init__(self, params, opt_state):
        self.params = params
        self.opt_state = opt_state


class TestFTOptimizer:
    def test_commit_applies_update(self):
        manager = MagicMock()
        manager.should_commit.return_value = True
        opt = FTOptimizer(manager, optax.sgd(0.5), jit=False)
        h = _Holder({"w": jnp.ones(3)}, None)
        h.opt_state = opt.init(h.params)
        assert opt.apply(h, {"w": jnp.full(3, 2.0)})
        np.testing.assert_allclose(h.params["w"], np.zeros(3))

    def test_abort_leaves_holder_unchanged(self):
        manager = MagicMock()
        manager.should_commit.return_value = False
        opt = FTOptimizer(manager, optax.sgd(0.5), jit=False)
        params = {"w": jnp.ones(3)}
        h = _Holder(params, opt.init(params))
        state = h.opt_state
        assert not opt.apply(h, {"w": jnp.full(3, 2.0)})
        assert h.params is params
        assert h.opt_state is state

    def test_heal_during_vote_uses_restored_params(self):
        """The vote may restore healed state into the holder; the update
        must read the holder AFTER the vote (regression: stale-snapshot
        update diverged healed replicas)."""
        manager = MagicMock()
        opt = FTOptimizer(manager, optax.sgd(1.0), jit=False)
        h = _Holder({"w": jnp.zeros(2)}, None)
        h.opt_state = opt.init(h.params)

        def vote_and_heal():
            h.params = {"w": jnp.full(2, 10.0)}  # healed state arrives
            return True

        manager.should_commit.side_effect = vote_and_heal
        opt.apply(h, {"w": jnp.ones(2)})
        np.testing.assert_allclose(h.params["w"], np.full(2, 9.0))

    def test_begin_step_calls_manager(self):
        manager = MagicMock()
        opt = FTOptimizer(manager, optax.sgd(0.1), jit=False)
        opt.begin_step()
        manager.step.assert_called_once()


class TestOptimizerWrapper:
    def test_reference_loop_shape(self):
        manager = MagicMock()
        manager.should_commit.return_value = True
        w = OptimizerWrapper(manager, optax.sgd(1.0), {"w": jnp.ones(2)})
        w.zero_grad()
        manager.step.assert_called_once()
        w.grads = {"w": jnp.ones(2)}
        assert w.step()
        np.testing.assert_allclose(w.params["w"], np.zeros(2))
        sd = w.state_dict()
        w.load_state_dict(sd)
        np.testing.assert_allclose(w.params["w"], np.zeros(2))


class TestDistributedSampler:
    def test_2d_grid_flattening(self):
        # reference data.py:68-77: global_rank = rank + num_replicas * group
        s = DistributedSampler(dataset_size=100, replica_group=1,
                               num_replica_groups=3, rank=1, num_replicas=2,
                               batch_size=4, shuffle=False)
        assert s.global_rank == 1 + 2 * 1
        assert s.global_world_size == 6
        first = next(iter(s))
        np.testing.assert_array_equal(first, [3, 9, 15, 21])

    def test_partition_disjoint_and_complete(self):
        n, groups, ranks = 64, 2, 2
        seen = []
        for g in range(groups):
            for r in range(ranks):
                s = DistributedSampler(n, g, groups, r, ranks, batch_size=4,
                                       shuffle=True, seed=7)
                for b in s:
                    seen.extend(b.tolist())
        assert sorted(seen) == list(range(n))

    def test_shuffle_deterministic_per_epoch(self):
        a = DistributedSampler(50, 0, 1, batch_size=5, seed=3)
        b = DistributedSampler(50, 0, 1, batch_size=5, seed=3)
        assert [x.tolist() for x in a] == [x.tolist() for x in b]
        a.set_epoch(1)
        b.set_epoch(0)
        assert [x.tolist() for x in a] != [x.tolist() for x in b]

    def test_resume_state(self):
        s = DistributedSampler(40, 0, 1, batch_size=4, seed=1)
        it = iter(s)
        first_two = [next(it).tolist(), next(it).tolist()]
        state = s.state_dict()

        s2 = DistributedSampler(40, 0, 1, batch_size=4, seed=999)
        s2.load_state_dict(state)
        rest = [b.tolist() for b in s2]
        full = DistributedSampler(40, 0, 1, batch_size=4, seed=1)
        assert first_two + rest == [b.tolist() for b in full]

    def test_batch_iterator_epochs(self):
        data = {"x": np.arange(8, dtype=np.float32)}
        s = DistributedSampler(8, 0, 1, batch_size=4, shuffle=False)
        it = BatchIterator(data, s)
        batches = [next(it)["x"].tolist() for _ in range(4)]
        assert batches[0] == batches[2]  # epoch wrapped

    def test_validation(self):
        with pytest.raises(ValueError):
            DistributedSampler(10, replica_group=3, num_replica_groups=2)
        with pytest.raises(ValueError):
            DistributedSampler(10, 0, 1, rank=5, num_replicas=2)
