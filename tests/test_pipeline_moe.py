"""Pipeline parallelism + MoE/expert parallelism tests (8-device CPU mesh)."""

import jax
import pytest
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from torchft_tpu.models import Transformer, TransformerConfig, causal_lm_loss
from torchft_tpu.models.moe import ep_rules
from torchft_tpu.models.transformer import moe_lm_loss
from torchft_tpu.parallel import apply_rules, make_mesh, shard_tree
from torchft_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_spec,
    stack_layer_params,
    transformer_pipeline_forward,
)

# Compile-heavy tier: pallas interpret mode + sharded jit dominate suite
# wall-clock; scripts/test.sh runs these after the fast unit tier.
pytestmark = pytest.mark.heavy


def small_cfg(**kw):
    base = dict(vocab_size=128, num_layers=4, embed_dim=64, num_heads=4,
                hidden_dim=128, max_seq_len=32, dtype=jnp.float32)
    base.update(kw)
    return TransformerConfig(**base)


class TestPipeline:
    def test_matches_sequential(self):
        """Pipelined forward == plain forward, bitwise-close."""
        cfg = small_cfg()
        model = Transformer(cfg)
        tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, 128)
        params = model.init(jax.random.key(0), tokens)
        ref = model.apply(params, tokens)

        mesh = make_mesh({"pp": 4, "dp": 2})
        with mesh:
            out = jax.jit(lambda p, t: transformer_pipeline_forward(
                cfg, p, t, mesh, n_micro=4))(params, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=2e-3)

    def test_differentiable(self):
        cfg = small_cfg(num_layers=2)
        model = Transformer(cfg)
        # B/n_micro must divide the dp axis (microbatches shard over dp)
        tokens = jax.random.randint(jax.random.key(1), (16, 8), 0, 128)
        params = model.init(jax.random.key(0), tokens)
        mesh = make_mesh({"pp": 2, "dp": 4})

        def loss_pp(p, t):
            return causal_lm_loss(
                transformer_pipeline_forward(cfg, p, t, mesh, n_micro=2), t)

        def loss_ref(p, t):
            return causal_lm_loss(model.apply(p, t), t)

        with mesh:
            g_pp = jax.jit(jax.grad(loss_pp))(params, tokens)
        g_ref = jax.grad(loss_ref)(params, tokens)
        for a, b in zip(jax.tree_util.tree_leaves(g_pp),
                        jax.tree_util.tree_leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-4, rtol=3e-3)

    def test_stage_params_are_sharded(self):
        """Stacked layer weights actually live distributed over pp."""
        cfg = small_cfg()
        model = Transformer(cfg)
        tokens = jnp.ones((4, 8), jnp.int32)
        params = model.init(jax.random.key(0), tokens)
        mesh = make_mesh({"pp": 4, "dp": 2})
        _, stacked = stack_layer_params(params, cfg.num_layers, 4)
        placed = jax.device_put(stacked, pipeline_spec(stacked, mesh))
        leaf = jax.tree_util.tree_leaves(placed)[0]
        assert leaf.sharding.spec[0] == "pp"
        # per-device shard holds 1 stage of 1 layer
        assert leaf.addressable_shards[0].data.shape[0] == 1

    def test_pipeline_apply_identity_stages(self):
        mesh = make_mesh({"pp": 4, "dp": 2})
        x = jnp.arange(32.0).reshape(8, 4)
        stacked = {"b": jnp.zeros((4, 1, 4))}  # 4 stages, zero bias

        def stage_fn(sp, h):
            return h + sp["b"][0]

        out = pipeline_apply(stage_fn, stacked, x, n_micro=4, mesh=mesh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))


class TestMoE:
    def test_moe_forward_shapes_and_loss(self):
        cfg = small_cfg(moe_experts=8, moe_top_k=2)
        model = Transformer(cfg)
        tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 128)
        params = model.init(jax.random.key(0), tokens)
        logits = model.apply(params, tokens)
        assert logits.shape == (2, 16, 128)
        loss = moe_lm_loss(model, params, tokens)
        plain = causal_lm_loss(logits, tokens)
        # aux loss strictly adds
        assert float(loss) > float(plain)

    def test_top1_is_single_expert_mix(self):
        """top_k=1: output must equal the argmax expert's MLP applied to x
        (verifies the dense-dispatch combine einsum end to end)."""
        import flax.linen as nn

        from torchft_tpu.models.moe import MoEMLP

        m = MoEMLP(num_experts=4, mlp_dim=32, top_k=1, dtype=jnp.float32)
        x = jax.random.normal(jax.random.key(0), (2, 8, 16))
        variables = m.init(jax.random.key(1), x)
        out = m.apply(variables, x)
        assert out.shape == x.shape

        p = variables["params"]
        logits = x @ p["router"]["kernel"]
        top = np.asarray(jnp.argmax(logits, axis=-1))  # [2, 8]
        expected = np.zeros_like(np.asarray(x))
        for b in range(x.shape[0]):
            for s in range(x.shape[1]):
                e = top[b, s]
                h = np.asarray(
                    nn.silu(x[b, s] @ p["wi_gate"][e])
                    * (x[b, s] @ p["wi_up"][e]))
                expected[b, s] = h @ np.asarray(p["wo"][e])
        np.testing.assert_allclose(np.asarray(out), expected,
                                   atol=1e-5, rtol=1e-5)

    def test_ep_sharded_training_step(self):
        """Expert dim sharded over ep; one jitted train step runs."""
        mesh = make_mesh({"dp": 2, "ep": 4})
        cfg = small_cfg(moe_experts=8, num_layers=2)
        model = Transformer(cfg)
        tokens = jnp.ones((4, 16), jnp.int32)
        params = model.init(jax.random.key(0), tokens)
        shardings = apply_rules(params, mesh, ep_rules())
        params = shard_tree(params, shardings)
        tokens = jax.device_put(
            tokens, NamedSharding(mesh, P(("dp",))))

        # expert stacks actually sharded
        leaf = params["params"]["layer_0"]["moe"]["wi_gate"]
        assert leaf.sharding.spec[0] == "ep"

        tx = optax.sgd(0.1)
        opt = tx.init(params)

        @jax.jit
        def step(p, o, t):
            loss, grads = jax.value_and_grad(
                lambda pp: moe_lm_loss(model, pp, t))(p)
            updates, o = tx.update(grads, o, p)
            return optax.apply_updates(p, updates), o, loss

        p1, o1, l1 = step(params, opt, tokens)
        p2, _, l2 = step(p1, o1, tokens)
        assert float(l2) < float(l1)
        assert p2["params"]["layer_0"]["moe"]["wi_gate"].sharding.spec[0] \
            == "ep"
