"""Unit battery for the shared transport substrate (tier-1).

``torchft_tpu/transport.py`` is the narrow waist every HTTP byte path
rides (docs/design/transport_substrate.md): ONE pooled ranged fetch
client, ONE ranged/bearer server core on a single asyncio loop, ONE
stripe-geometry source, ONE retry classification table, and weighted
per-path QoS. These tests pin the substrate's own contracts — the tier
suites (checkpointing/serving/ram_ckpt) pin the protocols built on it.
"""

import json
import os
import socket
import tempfile
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from torchft_tpu import chaos, transport
from torchft_tpu.chaos import parse_spec
from torchft_tpu.communicator import shard_bounds
from torchft_tpu.transport import (
    ConnectionPool,
    PushRejectedError,
    QOS_WEIGHTS,
    QoS,
    QoSScheduler,
    chunk_spans,
    classify,
    fetch_json,
    looks_peer_dead,
    push_ranged,
    qos_for_request,
    qos_from_header,
    serve_http,
    serve_ranged_bytes,
    serve_ranged_file,
)

pytestmark = pytest.mark.substrate


def _serve(route):
    srv = serve_http("127.0.0.1", 0, route, name="substrate-test")
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


class TestGeometry:
    def test_chunk_spans_is_shard_bounds(self):
        total, max_chunk = 10_000_001, 1 << 20
        spans = chunk_spans(total, max_chunk)
        n = -(-total // max_chunk)  # same COUNT as ceil-division loops
        assert len(spans) == n
        b = shard_bounds(total, n)
        assert spans == [(int(b[i]), int(b[i + 1])) for i in range(n)]

    def test_spans_cover_and_balance(self):
        spans = chunk_spans(1000, 300)
        assert spans[0][0] == 0 and spans[-1][1] == 1000
        for (_, e0), (s1, _) in zip(spans, spans[1:]):
            assert e0 == s1
        sizes = [e - s for s, e in spans]
        assert all(sz <= 300 for sz in sizes)
        # balanced: never the runt a naive range() tail produces
        assert max(sizes) - min(sizes) <= 1

    def test_base_offset_and_empty(self):
        assert chunk_spans(0, 100) == []
        assert chunk_spans(-5, 100) == []
        spans = chunk_spans(10, 4, base=100)
        assert spans[0][0] == 100 and spans[-1][1] == 110


class TestClassification:
    def test_http_503_window_vs_shutdown(self):
        def err(code, reason):
            return urllib.error.HTTPError("http://x", code, reason, {},
                                          None)
        assert classify(err(503, "serve window closed (commit)")) is True
        assert classify(err(503, "shutting down")) is False
        assert classify(err(404, "unknown step")) is False

    def test_registered_types_take_precedence(self):
        class _Fatal(RuntimeError):
            pass

        class _Soft(RuntimeError):
            pass

        transport.register_fatal(_Fatal)
        transport.register_transient(_Soft)
        assert classify(_Fatal("x")) is False
        assert classify(_Soft("x")) is True
        # the tiers' registrations landed at import time
        from torchft_tpu.checkpoint_io import CheckpointCorruptError
        from torchft_tpu.checkpointing import (HealCorruptError,
                                               LeafDigestError)
        assert classify(HealCorruptError("bad donor")) is False
        assert classify(CheckpointCorruptError("torn")) is False
        assert classify(LeafDigestError("leaf 3 crc")) is True

    def test_looks_peer_dead_walks_wrappers(self):
        inner = ConnectionRefusedError(111, "Connection refused")
        wrapped = urllib.error.URLError(inner)
        assert looks_peer_dead(wrapped) is True
        assert looks_peer_dead(TimeoutError("slow")) is False


class TestQoS:
    def test_header_and_route_defaults(self):
        assert qos_from_header("heal", QoS.DEMOTION) is QoS.HEAL
        # unknown and RING (never carried over HTTP) fall to the default
        assert qos_from_header("bogus", QoS.HEAL) is QoS.HEAL
        assert qos_from_header("ring", QoS.HEAL) is QoS.HEAL
        assert qos_for_request("GET", "/publish/3", {}) is QoS.PUBLICATION
        assert qos_for_request("PUT", "/ramckpt/7", {}) is QoS.DEMOTION
        assert qos_for_request("GET", "/checkpoint/3", {}) is QoS.HEAL
        hdrs = transport._Headers(
            {transport.QOS_HEADER.lower(): "publication"})
        assert qos_for_request("GET", "/checkpoint/3",
                               hdrs) is QoS.PUBLICATION

    def test_weighted_fairness_under_contention(self):
        """With every class fully backlogged, per-round grants track the
        DRR weights exactly: the moment the highest class drains its
        queue, each lower class has completed ~weight-proportionally
        many chunks — the saturating-publication leg can slow a heal,
        never starve it (and vice versa)."""
        import asyncio

        done = {c: 0 for c in QoS}
        per_class = 64  # chunks queued per class up front

        async def drive():
            sched = QoSScheduler(transport._Counters())
            chunk = QoSScheduler.QUANTUM  # 1 deficit quantum per chunk

            async def one(c):
                await sched.grant(c, chunk)
                done[c] += 1

            tasks = [asyncio.get_event_loop().create_task(one(c))
                     for c in QoS for _ in range(per_class)]
            while done[QoS.RING] < per_class:
                await asyncio.sleep(0)
            snapshot = dict(done)
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            if sched._pump_task is not None:
                sched._pump_task.cancel()
                await asyncio.gather(sched._pump_task,
                                     return_exceptions=True)
            return snapshot

        snap = asyncio.new_event_loop().run_until_complete(drive())
        # RING (weight 8) drained first; every class made progress —
        # nobody starved while the highest class saturated the plane.
        assert snap[QoS.RING] == per_class
        assert all(snap[c] > 0 for c in QoS)
        # Completion ratios track weights (1-round slack for the
        # snapshot landing mid-round).
        rounds = per_class / QOS_WEIGHTS[QoS.RING]
        for c in (QoS.HEAL, QoS.PUBLICATION, QoS.DEMOTION):
            expect = rounds * QOS_WEIGHTS[c]
            assert abs(snap[c] - expect) <= QOS_WEIGHTS[c] + 1, (
                f"{c.name}: {snap[c]} vs expected ~{expect}")
        # strict ordering under full backlog
        assert snap[QoS.HEAL] > snap[QoS.PUBLICATION] > \
            snap[QoS.DEMOTION]


class TestServerCore:
    def test_pool_reuse_avoids_redial(self):
        def route(h):
            body = b"ok"
            h.send_response(200)
            h.send_header("Content-Length", "2")
            h.end_headers()
            h.wfile.write(body)

        srv, base = _serve(route)
        pool = ConnectionPool()
        try:
            for _ in range(3):
                with pool.request(f"{base}/x", 5.0, None) as r:
                    assert r.read() == b"ok"
            assert pool.redials == 1
            assert pool.redials_avoided == 2
        finally:
            pool.close()
            srv.shutdown()
            srv.server_close()

    def test_ranged_bytes_200_206_416(self):
        payload = bytes(range(256)) * 40
        view = memoryview(payload)

        def route(h):
            serve_ranged_bytes(h, view, 10.0)

        srv, base = _serve(route)
        pool = ConnectionPool()
        try:
            with pool.request(f"{base}/img", 5.0, None) as r:
                assert r.read() == payload
            with pool.request(f"{base}/img", 5.0, None,
                              headers={"Range": "bytes=100-199"}) as r:
                assert r.status == 206
                assert r.headers["Content-Range"] == \
                    f"bytes 100-199/{len(payload)}"
                assert r.read() == payload[100:200]
            with pytest.raises(urllib.error.HTTPError) as ei:
                pool.request(f"{base}/img", 5.0, None,
                             headers={"Range": f"bytes={len(payload)}-"})
            assert ei.value.code == 416
        finally:
            pool.close()
            srv.shutdown()
            srv.server_close()

    def test_bearer_gate(self):
        def route(h):
            if not transport.check_bearer_auth(h, "s3cret"):
                return
            h.send_response(200)
            h.send_header("Content-Length", "2")
            h.end_headers()
            h.wfile.write(b"in")

        srv, base = _serve(route)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                fetch_json(f"{base}/x", stall=5.0)
            assert ei.value.code == 401
            req = urllib.request.Request(
                f"{base}/x",
                headers={"Authorization": "Bearer s3cret"})
            with urllib.request.urlopen(req, timeout=5) as r:
                assert r.read() == b"in"
        finally:
            srv.shutdown()
            srv.server_close()

    def test_sendfile_path_serves_and_counts(self):
        payload = os.urandom(1 << 20)
        f = tempfile.NamedTemporaryFile()
        f.write(payload)
        f.flush()
        fobj = open(f.name, "rb")

        def route(h):
            serve_ranged_file(h, fobj, len(payload), 10.0)

        before = transport.metrics()["transport_sendfile_bytes_total"]
        srv, base = _serve(route)
        pool = ConnectionPool()
        try:
            with pool.request(f"{base}/f", 5.0, None,
                              headers={"Range": "bytes=4096-8191"}) as r:
                assert r.read() == payload[4096:8192]
            if transport.async_hosting_enabled():
                # The drain task bumps the counter after the kernel
                # send — the client can observe the bytes first.
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    after = transport.metrics()[
                        "transport_sendfile_bytes_total"]
                    if after - before >= 4096:
                        break
                    time.sleep(0.01)
                assert after - before >= 4096
        finally:
            pool.close()
            srv.shutdown()
            srv.server_close()
            fobj.close()
            f.close()

    def test_push_ranged_faults_progress_and_422(self):
        got = {}
        reject = {"on": False}

        def route(h):
            if reject["on"]:
                h.send_error(422, "digest mismatch")
                return
            n = int(h.headers.get("Content-Length", "0"))
            body = h.rfile.read(n)
            rng = h.headers.get("Content-Range")
            got[rng] = body
            h.send_response(200)
            h.send_header("Content-Length", "0")
            h.end_headers()

        srv, base = _serve(route)
        payload = memoryview(os.urandom(100_000))
        faults, deltas = [], []
        try:
            pushed = push_ranged(
                base, "/ramckpt/7", payload, chunk_bytes=30_000,
                fault=lambda: faults.append(1),
                progress=deltas.append)
            assert pushed == len(payload)
            # one fault hook + one progress tick per chunk_spans chunk
            n_chunks = len(chunk_spans(len(payload), 30_000))
            assert len(faults) == n_chunks
            assert sum(deltas) == len(payload)
            assert b"".join(
                got[k] for k in sorted(
                    got, key=lambda r: int(r.split()[1].split("-")[0]))
            ) == bytes(payload)
            reject["on"] = True
            with pytest.raises(PushRejectedError):
                push_ranged(base, "/ramckpt/8", payload,
                            chunk_bytes=30_000)
        finally:
            srv.shutdown()
            srv.server_close()

    def test_metrics_keys_frozen(self):
        m = transport.metrics()
        assert set(m) == {
            "transport_qos_ring_bytes_total",
            "transport_qos_heal_bytes_total",
            "transport_qos_publication_bytes_total",
            "transport_qos_demotion_bytes_total",
            "transport_qos_waits_total",
            "transport_conns_total",
            "transport_requests_total",
            "transport_sendfile_bytes_total",
        }
        assert all(isinstance(v, float) for v in m.values())


class TestChaosSeam:
    """The chaos ``serve:``/``heal:`` channels keep working injected at
    the substrate seam: client-side begin/wrap_reader at the fetch
    sites, endpoint_reborn at the (now substrate-hosted) server bind."""

    def _state(self):
        return {"w": np.arange(64, dtype=np.float32),
                "b": np.ones((8, 8), dtype=np.float32)}

    def test_heal_kill_latch_and_rebirth_through_substrate(self):
        from torchft_tpu.checkpointing import CheckpointServer

        state = self._state()
        chaos.install(parse_spec("seed=3;heal:latency_ms=0"))
        try:
            srv = CheckpointServer(lambda: state, bind_host="127.0.0.1")
            srv.allow_checkpoint(1)
            addr = srv.address()
            netloc = addr.split("//")[1].split("/")[0]
            port = int(netloc.rsplit(":", 1)[1])
            sched = chaos.active()
            sched.kill_endpoint(f"heal:{netloc}")
            with pytest.raises(Exception) as ei:
                CheckpointServer.load_from_address(
                    addr, self._state(), device_put=False)
            assert looks_peer_dead(ei.value) or "refused" in \
                str(ei.value).lower() or "killed" in str(ei.value).lower()
            srv.shutdown()
            # A replacement binding the same port must not inherit the
            # dead latch — the rebirth call survives the hosting swap.
            srv2 = CheckpointServer(lambda: state, bind_host="127.0.0.1",
                                    bind_port=port)
            try:
                srv2.allow_checkpoint(1)
                got = CheckpointServer.load_from_address(
                    srv2.address(), self._state(), device_put=False)
                np.testing.assert_array_equal(got["w"], state["w"])
            finally:
                srv2.shutdown()
        finally:
            chaos.uninstall()

    def test_serve_short_reads_never_place_bad_bytes(self):
        """crc-verify-before-place at the seam: a publication subscriber
        fed short/reset streams retries until verified, and the placed
        weights are bitwise-identical — torn bytes never surface."""
        from torchft_tpu.retry import RetryPolicy
        from torchft_tpu.serving import (PublicationServer,
                                         WeightPublisher,
                                         WeightSubscriber)

        state = self._state()
        pub = WeightPublisher()
        srv = PublicationServer(pub, bind_host="127.0.0.1")
        netloc = srv.address().split("//")[1].split("/")[0]
        chaos.install(parse_spec(
            f"seed=11;serve:short_rate=0.4,max_faults=4"))
        sub = None
        try:
            pub.publish(state, step=1)
            sub = WeightSubscriber(
                srv.address(), self._state(),
                retry_policy=RetryPolicy(max_attempts=8,
                                         base_delay_ms=10.0,
                                         max_delay_ms=50.0))
            assert sub.sync() is True
            got = sub.weights()
            np.testing.assert_array_equal(got["w"], state["w"])
            np.testing.assert_array_equal(got["b"], state["b"])
        finally:
            chaos.uninstall()
            srv.shutdown()
