"""Tests for the observability tier (:mod:`torchft_tpu.tracing`,
docs/design/observability.md): the span ring's bounds and context
propagation, the flight recorder's triggers (vote abort, latched
CommunicatorError, heal failover, policy escalation, crash exit), the
``/trace.json`` + ``/metrics`` endpoints over real HTTP, the fleet
merger's ``(quorum_id, epoch, step)`` alignment, event-log monotonic
ordering — and the nightly 2-group chaos round: an injected ring reset
must leave a Perfetto-loadable flight-recorder dump on BOTH groups
whose spans attribute the abort to the fault, with
``scripts/tracefleet.py`` merging both groups' live ``/trace.json``
into one timeline."""

import json
import os
import sys
import threading
import urllib.request
from unittest.mock import MagicMock

import numpy as np
import pytest

from torchft_tpu import tracing
from torchft_tpu._native import QuorumResult
from torchft_tpu.communicator import (CommunicatorError,
                                      DummyCommunicator)
from torchft_tpu.manager import Manager

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def quorum_result(quorum_id=1, max_step=1, replica_rank=0, max_rank=0,
                  replica_world_size=2, max_world_size=2, heal=False,
                  store_address=""):
    return QuorumResult(
        quorum_id=quorum_id, recover_manager_address="manager1:1234",
        store_address=store_address, max_step=max_step,
        max_rank=max_rank, max_world_size=max_world_size,
        replica_rank=replica_rank,
        replica_world_size=replica_world_size, heal=heal)


def make_manager(client=None, comm=None, replica_id="obs0", **kw):
    if client is None:
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = True
    return Manager(
        comm=comm or DummyCommunicator(),
        load_state_dict=MagicMock(),
        state_dict=lambda: {"w": np.arange(8, dtype=np.float32)},
        min_replica_size=1,
        use_async_quorum=False,
        rank=0, world_size=1,
        replica_id=replica_id,
        _manager_client=client,
        **kw,
    )


# ------------------------------------------------------------- span ring


class TestSpanRing:
    def test_ring_bounded_to_last_k_steps(self):
        tr = tracing.Tracer(steps=3, enabled=True)
        for step in range(10):
            tr.set_context(step=step)
            with tr.span("vote"):
                pass
        steps_seen = {s["step"] for s in tr.spans()}
        assert steps_seen == {7, 8, 9}
        assert tr.spans_total == 10  # recorded, then evicted

    def test_per_step_span_cap_counts_drops(self):
        tr = tracing.Tracer(steps=2, enabled=True, max_spans_per_step=5)
        tr.set_context(step=1)
        for _ in range(9):
            with tr.span("ring"):
                pass
        assert len(tr.spans()) == 5
        assert tr.spans_dropped == 4
        assert tr.metrics()["trace_spans_dropped"] == 4.0

    def test_context_snapshot_is_consistent(self):
        """A span captures the context in force at its START even if
        the context moves before it finishes (copy-on-write)."""
        tr = tracing.Tracer(steps=4, enabled=True)
        tr.set_context(step=5, quorum_id=2)
        sp = tr.span("heal")
        tr.set_context(step=6, quorum_id=3)
        sp.__exit__(None, None, None)
        rec = tr.spans()[0]
        assert rec["step"] == 5 and rec["quorum_id"] == 2

    def test_tags_and_steps_window_param(self):
        tr = tracing.Tracer(steps=8, enabled=True)
        for step in (1, 2, 3):
            tr.set_context(step=step)
            with tr.span("fetch_wait", bucket=step * 10):
                pass
        last2 = tr.spans(steps=2)
        assert [s["step"] for s in last2] == [2, 3]
        assert [s["bucket"] for s in last2] == [20, 30]
        # steps=0 means ZERO steps — a -0 slice must not invert it
        # into the whole ring.
        assert tr.spans(steps=0) == []

    def test_disabled_tracer_is_noop(self):
        tr = tracing.Tracer(steps=4, enabled=False)
        with tr.span("vote", x=1):
            pass
        assert tr.spans() == []
        assert tr.spans_total == 0
        # and the context manager is the shared singleton (no per-call
        # allocation on the hot path)
        assert tr.span("a") is tr.span("b")

    def test_exception_tags_error_and_closes(self):
        tr = tracing.Tracer(steps=4, enabled=True)
        with pytest.raises(ValueError):
            with tr.span("ring"):
                raise ValueError("connection reset (injected)")
        rec = tr.spans()[0]
        assert "connection reset" in rec["error"]
        assert not tr.open_spans()

    def test_thread_safety_smoke(self):
        tr = tracing.Tracer(steps=4, enabled=True)
        tr.set_context(step=1)

        def worker():
            for _ in range(200):
                with tr.span("ring"):
                    pass

        ts = [threading.Thread(target=worker) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert tr.spans_total == 800


# ---------------------------------------------------------- manager spans


class TestManagerSpans:
    def test_step_protocol_records_stage_spans(self):
        m = make_manager()
        try:
            m.step()
            fut = m.allreduce({"g": np.ones(4, np.float32)})
            fut.result()
            assert m.should_commit()
            stages = {s["stage"] for s in m.tracer().spans()}
            assert {"quorum", "fetch_dispatch", "fetch_wait", "put",
                    "drain", "vote"} <= stages
            # every span carries the alignment coordinates
            for s in m.tracer().spans():
                assert s["replica_id"] == "obs0"
                assert s["quorum_id"] == 1
                assert s["policy_name"]
        finally:
            m.shutdown()

    def test_vote_span_tags_decision(self):
        m = make_manager()
        try:
            m.step()
            m.should_commit()
            votes = [s for s in m.tracer().spans()
                     if s["stage"] == "vote"]
            assert votes and votes[-1]["decision"] is True
        finally:
            m.shutdown()

    def test_tracing_opt_out_kwarg(self):
        m = make_manager(tracing=False)
        try:
            m.step()
            m.should_commit()
            assert m.tracer().spans() == []
            # counters still present and numeric
            assert m.metrics()["trace_spans_total"] == 0.0
        finally:
            m.shutdown()


# ------------------------------------------------------- flight recorder


class TestFlightRecorder:
    def test_disabled_without_dir(self, monkeypatch):
        monkeypatch.delenv("TORCHFT_FLIGHT_DIR", raising=False)
        m = make_manager()
        try:
            assert m.flight_recorder() is not None
            assert not m.flight_recorder().enabled
            assert m.flight_recorder().dump("manual") is None
        finally:
            m.shutdown()

    def test_vote_abort_dumps(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TORCHFT_FLIGHT_DIR", str(tmp_path))
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = False
        m = make_manager(client=client, replica_id="abort0")
        try:
            m.step()
            assert not m.should_commit()
            files = [f for f in os.listdir(tmp_path)
                     if "vote_abort" in f]
            assert len(files) == 1
            body = json.loads((tmp_path / files[0]).read_text())
            assert body["torchft"]["reason"] == "vote_abort"
            assert body["torchft"]["replica_id"].startswith("abort0")
            assert body["traceEvents"], "dump must carry the span ring"
            assert body["torchft"]["metrics"]["aborted_steps"] == 1
            assert isinstance(body["torchft"]["history"], list)
            assert m.metrics()["flight_dumps_total"] == 1.0
            assert m.metrics_info()["flight_last_path"].endswith(
                files[0])
        finally:
            m.shutdown()

    def test_latched_comm_error_dumps_once(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TORCHFT_FLIGHT_DIR", str(tmp_path))
        m = make_manager(replica_id="comm0")
        try:
            m.step()
            m.report_error(CommunicatorError("connection reset by peer"))
            m.report_error(CommunicatorError("second reset"))  # latched
            files = [f for f in os.listdir(tmp_path)
                     if "comm_error" in f]
            assert len(files) == 1
            body = json.loads((tmp_path / files[0]).read_text())
            assert "reset" in body["torchft"]["extra"]["error"]
        finally:
            m.shutdown()

    def test_dedupe_per_reason_and_step(self, tmp_path):
        tr = tracing.Tracer(steps=4, enabled=True)
        rec = tracing.FlightRecorder(tr, directory=str(tmp_path),
                                     replica_id="d0")
        try:
            tr.set_context(step=1)
            assert rec.dump("vote_abort") is not None
            assert rec.dump("vote_abort") is None  # same (reason, step)
            tr.set_context(step=2)
            assert rec.dump("vote_abort") is not None  # new step
            assert rec.dumps_total == 2
        finally:
            rec.close()

    def test_failed_write_rolls_back_dedupe_and_count(self, tmp_path):
        """A transient write failure (ENOSPC-class) must not consume
        the incident's dedup slot, the dump cap, or the counter — the
        SAME incident must dump once space clears, and
        flight_dumps_total must never claim a file that was never
        written."""
        tr = tracing.Tracer(steps=4, enabled=True)
        blocked = tmp_path / "flight"
        blocked.write_text("not a directory")  # makedirs -> raises
        rec = tracing.FlightRecorder(tr, directory=str(blocked),
                                     replica_id="e0")
        try:
            tr.set_context(step=7)
            assert rec.dump("vote_abort") is None  # write failed
            assert rec.dumps_total == 0
            blocked.unlink()  # "space clears"
            path = rec.dump("vote_abort")  # same (reason, step) again
            assert path is not None and os.path.exists(path)
            assert rec.dumps_total == 1
        finally:
            rec.close()

    def test_dedupe_tracks_steps_even_with_tracing_disabled(
            self, tmp_path):
        """TORCHFT_TRACING=0 + TORCHFT_FLIGHT_DIR is a supported combo
        (zero-overhead spans, incidents still recorded): the context —
        and with it the per-(reason, step) dedup and the filename stamp
        — must keep tracking steps with span recording off, or every
        later incident collapses onto step 0's dedup slot."""
        tr = tracing.Tracer(steps=4, enabled=False)
        rec = tracing.FlightRecorder(tr, directory=str(tmp_path),
                                     replica_id="off0")
        try:
            tr.set_context(step=100)
            p1 = rec.dump("vote_abort")
            tr.set_context(step=200)
            p2 = rec.dump("vote_abort")
            assert p1 is not None and p2 is not None
            assert "s100" in p1 and "s200" in p2
        finally:
            rec.close()

    def test_atexit_after_exception_hook(self, tmp_path):
        tr = tracing.Tracer(steps=4, enabled=True)
        rec = tracing.FlightRecorder(tr, directory=str(tmp_path),
                                     replica_id="crash0")
        try:
            with tr.span("ring"):
                pass
            # Simulate the unhandled-exception latch + process exit.
            tracing._note_crash("RuntimeError('boom')")
            tracing._atexit_dump()
            files = [f for f in os.listdir(tmp_path)
                     if "atexit_after_exception" in f]
            assert len(files) == 1
            body = json.loads((tmp_path / files[0]).read_text())
            assert body["torchft"]["extra"]["exception"] == \
                "RuntimeError('boom')"
        finally:
            rec.close()
            with tracing._CRASH_LOCK:
                tracing._CRASH_SEEN["seen"] = False
                tracing._CRASH_SEEN["what"] = ""

    def test_dump_is_perfetto_loadable_shape(self, tmp_path):
        """The dump IS a Chrome trace JSON object: traceEvents at the
        top level (phases within the frozen B/E/X/M set), sidecar data
        under a separate key — what Perfetto's JSON importer accepts."""
        tr = tracing.Tracer(steps=4, enabled=True)
        rec = tracing.FlightRecorder(tr, directory=str(tmp_path),
                                     replica_id="p0")
        try:
            tr.set_context(step=3, quorum_id=1, epoch=1,
                           replica_id="p0", policy_name="sync-f32")
            with tr.span("quorum"):
                pass
            path = rec.dump("manual")
            body = json.loads(open(path).read())
            assert set(ev["ph"] for ev in body["traceEvents"]) <= \
                {"X", "B", "E", "M"}
            assert body["torchft"]["format"] == tracing.FLIGHT_FORMAT
        finally:
            rec.close()


# --------------------------------------------------------- event ordering


class TestEventOrdering:
    def test_events_carry_monotonic_stamp_and_seq(self):
        """Satellite: events interleaved across threads/groups order by
        (t_mono_ns, seq) even under wall-clock steps — `t` alone can go
        BACKWARD when ntp slews."""
        m = make_manager()
        try:
            m.step()
            m.report_error(RuntimeError("x"))
            m.should_commit()
            events = m.history()
            assert events, "expected events"
            for e in events:
                assert "t" in e and "t_mono_ns" in e and "seq" in e
            seqs = [e["seq"] for e in events]
            assert seqs == sorted(seqs)
            assert len(set(seqs)) == len(seqs)
            monos = [e["t_mono_ns"] for e in events]
            assert monos == sorted(monos)
        finally:
            m.shutdown()


# ----------------------------------------------------------- HTTP exports


def _http_get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.getcode(), resp.read()


class TestHTTPEndpoints:
    def test_trace_json_over_real_http(self):
        m = make_manager(replica_id="http0")
        try:
            m.step()
            m.allreduce({"g": np.ones(4, np.float32)}).result()
            m.should_commit()
            base = m._ckpt_server.address()
            base = base[:base.rindex("/checkpoint/")]
            code, body = _http_get(base + "/trace.json?steps=8")
            assert code == 200
            trace = json.loads(body)
            names = {ev["name"] for ev in trace["traceEvents"]
                     if ev["ph"] == "X"}
            assert {"quorum", "vote"} <= names
        finally:
            m.shutdown()

    def test_metrics_prometheus_over_real_http(self):
        m = make_manager(replica_id="http1")
        try:
            m.step()
            m.should_commit()
            base = m._ckpt_server.address()
            base = base[:base.rindex("/checkpoint/")]
            code, body = _http_get(base + "/metrics")
            assert code == 200
            text = body.decode()
            assert "torchft_committed_steps" in text
            assert 'torchft_info{' in text
            assert 'policy_name="' in text
            assert 'replica_id="http1"' in text
        finally:
            m.shutdown()

    def test_bad_steps_param_is_400(self):
        m = make_manager()
        try:
            base = m._ckpt_server.address()
            base = base[:base.rindex("/checkpoint/")]
            with pytest.raises(urllib.error.HTTPError) as ei:
                _http_get(base + "/trace.json?steps=banana")
            assert ei.value.code == 400
        finally:
            m.shutdown()

    def test_unattached_server_404s(self):
        from torchft_tpu.checkpointing import CheckpointServer

        srv = CheckpointServer(lambda: {"x": np.zeros(1)})
        try:
            base = srv.address()
            base = base[:base.rindex("/checkpoint/")]
            with pytest.raises(urllib.error.HTTPError) as ei:
                _http_get(base + "/trace.json")
            assert ei.value.code == 404
        finally:
            srv.shutdown()


# ------------------------------------------------------------ fleet merge


def _synthetic_trace(replica, offset_us, steps=(1, 2)):
    """A hand-built per-group trace whose quorum spans start exactly
    ``offset_us`` later than group time 0 — known ground truth for the
    aligner."""
    events = [{"ph": "M", "name": "process_name", "pid": 99,
               "args": {"name": replica}}]
    for step in steps:
        base = offset_us + step * 1000.0
        for i, stage in enumerate(("quorum", "vote")):
            events.append({
                "name": stage, "cat": "torchft", "ph": "X",
                "ts": base + i * 100.0, "dur": 50.0, "pid": 99,
                "tid": i + 1,
                "args": {"replica_id": replica, "quorum_id": 1,
                         "epoch": 1, "step": step,
                         "policy_name": "sync-f32"},
            })
    return {"traceEvents": events}


class TestMergeTraces:
    def test_aligns_on_quorum_epoch_step(self):
        a = _synthetic_trace("g0", offset_us=0.0)
        b = _synthetic_trace("g1", offset_us=123456.0)  # skewed clock
        merged = tracing.merge_traces([a, b])
        assert merged["torchft"]["aligned_on"] == [
            "quorum_id", "epoch", "step"]
        # g1's offset recovered exactly: after alignment, same-key
        # quorum spans coincide.
        assert merged["torchft"]["offsets_us"] == [0.0, -123456.0]
        assert merged["torchft"]["reference_group"] == "g0"
        assert merged["torchft"]["unaligned_groups"] == []
        by_group = {}
        for ev in merged["traceEvents"]:
            if ev.get("ph") == "X" and ev["name"] == "quorum" \
                    and ev["args"]["step"] == 1:
                by_group[ev["pid"]] = ev["ts"]
        assert len(by_group) == 2
        ts = list(by_group.values())
        assert abs(ts[0] - ts[1]) < 1e-6
        # distinct pids + process names survive
        names = {ev["args"]["name"] for ev in merged["traceEvents"]
                 if ev.get("ph") == "M"
                 and ev.get("name") == "process_name"}
        assert names == {"g0", "g1"}

    def test_no_shared_keys_flagged_unaligned(self):
        a = _synthetic_trace("g0", 0.0, steps=(1,))
        b = _synthetic_trace("g1", 500.0, steps=(9,))
        merged = tracing.merge_traces([a, b])
        assert merged["torchft"]["offsets_us"] == [0.0, 0.0]
        # no silent scatter: the unalignable group is NAMED
        assert merged["torchft"]["unaligned_groups"] == ["g1"]

    def test_reference_is_best_connected_group(self):
        """A first group with an empty/disjoint ring (cold restart,
        tracing off) must not blank the fleet's alignment: the
        reference is the group sharing keys with the most others."""
        empty = {"traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 9,
             "args": {"name": "cold0"}}]}
        b = _synthetic_trace("g1", 0.0)
        c = _synthetic_trace("g2", 777.0)
        merged = tracing.merge_traces([empty, b, c])
        assert merged["torchft"]["reference_group"] in ("g1", "g2")
        assert merged["torchft"]["unaligned_groups"] == ["cold0"]
        # g1/g2 still align with each other
        offs = merged["torchft"]["offsets_us"]
        assert 0.0 in (offs[1], offs[2])
        assert abs(abs(offs[1] - offs[2]) - 777.0) < 1e-6


class TestTracefleetCLI:
    def test_merges_two_live_groups_over_http(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import tracefleet
        finally:
            sys.path.pop(0)

        managers = []
        addrs = []
        try:
            for i in range(2):
                m = make_manager(replica_id=f"fleet{i}")
                m.step()
                m.allreduce({"g": np.ones(4, np.float32)}).result()
                m.should_commit()
                managers.append(m)
                addrs.append(m._ckpt_server.address())
            out = tmp_path / "fleet.json"
            rc = tracefleet.main(addrs + ["--out", str(out),
                                          "--steps", "16"])
            assert rc == 0
            merged = json.loads(out.read_text())
            pids = {ev["pid"] for ev in merged["traceEvents"]}
            assert pids == {1, 2}
            names = {ev["args"]["name"] for ev in merged["traceEvents"]
                     if ev.get("ph") == "M"
                     and ev.get("name") == "process_name"}
            assert names == {"fleet0", "fleet1"}
            stages = {ev["name"] for ev in merged["traceEvents"]
                      if ev.get("ph") == "X"}
            assert {"quorum", "vote"} <= stages
        finally:
            for m in managers:
                m.shutdown()

    def test_dead_group_skipped_not_fatal(self, tmp_path):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import tracefleet
        finally:
            sys.path.pop(0)

        m = make_manager(replica_id="alive0")
        try:
            m.step()
            m.should_commit()
            out = tmp_path / "fleet.json"
            rc = tracefleet.main(
                [m._ckpt_server.address(), "127.0.0.1:1",  # dead
                 "--out", str(out), "--timeout", "2"])
            assert rc == 0
            assert json.loads(out.read_text())["traceEvents"]
        finally:
            m.shutdown()


# ------------------------------------------- nightly chaos acceptance


class _PairHub:
    """Two-rank rendezvous hub pairing each rank's n-th wire op with
    the peer's n-th and resolving both with the canonical-order fold —
    the native-free 2-group ring used across the policy tests."""

    def __init__(self):
        self.lock = threading.Lock()
        self.counts = {}
        self.pending = {}

    def submit(self, rank, buffers, origs):
        from concurrent.futures import Future

        from torchft_tpu.communicator import _upcast_buffers

        fut = Future()
        with self.lock:
            idx = self.counts.get(rank, 0)
            self.counts[rank] = idx + 1
            entry = self.pending.setdefault(idx, {})
            entry[rank] = (list(buffers),
                           [np.dtype(d) for d in origs], fut)
            ready = len(entry) == 2
            if ready:
                del self.pending[idx]
        if ready:
            vals = {r: _upcast_buffers(b, o)
                    for r, (b, o, _f) in entry.items()}
            sums = [vals[0][i] + vals[1][i]
                    for i in range(len(vals[0]))]
            for _r, (_b, origs_r, f) in entry.items():
                f.set_result([np.array(s, dtype=d)
                              for s, d in zip(sums, origs_r)])
        return fut


class _PairComm(DummyCommunicator):
    def __init__(self, hub, rank):
        super().__init__(rank=rank, world_size=2)
        self._hub = hub

    def configure(self, store_addr, rank, world_size):
        self.configure_count += 1  # keep the pair's fixed rank/world

    def allreduce_wire(self, buffers, orig_dtypes, op="sum"):
        return self._hub.submit(self.rank(), buffers, orig_dtypes)


@pytest.mark.slow
@pytest.mark.nightly
class TestFlightRecorderChaosNightly:
    """Acceptance: a 2-group run with an injected ring reset (the
    ChaosCommunicator shim — the same CommunicatorError class a real
    TCP reset surfaces as) leaves a parseable, Perfetto-shaped
    flight-recorder dump on BOTH groups whose spans/extra attribute the
    abort to the fault, and tracefleet merges both groups' /trace.json
    into one timeline aligned on (quorum_id, epoch, step)."""

    def test_injected_ring_reset_leaves_attributable_dumps(
            self, tmp_path, monkeypatch):
        from torchft_tpu.chaos import ChaosCommunicator, ChaosSchedule

        monkeypatch.setenv("TORCHFT_FLIGHT_DIR", str(tmp_path))
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import tracefleet
        finally:
            sys.path.pop(0)

        RESET_STEP = 3  # 1-based step whose ring op resets

        class ResetOnce(ChaosSchedule):
            """Scripted: the RESET_STEP-th allreduce_wire op on each
            group fails post-submit with a connection reset."""

            def __init__(self):
                super().__init__(seed=0)
                self.n = 0
                self.lock = threading.Lock()

            def config_for(self, endpoint):
                return object()

            def decide(self, endpoint, op):
                from torchft_tpu.chaos import Decision

                with self.lock:
                    self.n += 1
                    n = self.n
                if n == RESET_STEP:
                    return Decision(endpoint=endpoint, op=op, n=n,
                                    delay_ms=0, fault="reset",
                                    phase="post", frac=1.0,
                                    blackhole_ms=0.0)
                return None

        hub = _PairHub()
        barrier = threading.Barrier(2)
        managers = {}
        errors = []
        done = threading.Barrier(2 + 1)

        def run_group(rank):
            try:
                client = MagicMock()
                client.quorum.return_value = quorum_result(
                    max_rank=rank, replica_rank=rank)
                client.should_commit.side_effect = (
                    lambda **kw: kw["should_commit"])
                comm = ChaosCommunicator(_PairComm(hub, rank),
                                         schedule=ResetOnce(),
                                         endpoint="ring")
                m = make_manager(client=client, comm=comm,
                                 replica_id=f"chaos{rank}")
                managers[rank] = m
                for _ in range(5):
                    barrier.wait(timeout=60)
                    m.step()
                    m.allreduce(
                        {"g": np.ones(64, np.float32)}).result()
                    m.should_commit()
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                try:
                    barrier.abort()
                except Exception:  # noqa: BLE001
                    pass
            finally:
                done.wait(timeout=60)

        ts = [threading.Thread(target=run_group, args=(r,))
              for r in range(2)]
        for t in ts:
            t.start()
        done.wait(timeout=120)
        for t in ts:
            t.join(timeout=60)
        try:
            assert not errors, errors

            # Both groups dumped on the latched reset, and the dumps
            # are parseable Chrome-trace JSON attributing the abort.
            for rank in range(2):
                dumps = [f for f in os.listdir(tmp_path)
                         if f.startswith(f"flight_chaos{rank}_")
                         and "comm_error" in f]
                assert len(dumps) == 1, sorted(os.listdir(tmp_path))
                body = json.loads((tmp_path / dumps[0]).read_text())
                side = body["torchft"]
                assert side["reason"] == "comm_error"
                assert "reset" in side["extra"]["error"]
                assert side["step"] == RESET_STEP
                assert side["metrics"]["trace_spans_total"] > 0
                phases = {ev["ph"] for ev in body["traceEvents"]}
                assert phases <= {"X", "B", "E", "M"}
                # the span ring covers the aborted step's pipeline
                span_steps = {ev["args"]["step"]
                              for ev in body["traceEvents"]
                              if ev["ph"] == "X"}
                assert RESET_STEP in span_steps
                # vote_abort fired at the same step too
                aborts = [f for f in os.listdir(tmp_path)
                          if f.startswith(f"flight_chaos{rank}_")
                          and "vote_abort" in f]
                assert aborts, sorted(os.listdir(tmp_path))

            # Fleet merge of both groups' live /trace.json.
            out = tmp_path / "fleet.json"
            addrs = [managers[r]._ckpt_server.address()
                     for r in range(2)]
            assert tracefleet.main(addrs + ["--out", str(out)]) == 0
            merged = json.loads(out.read_text())
            pids = {ev["pid"] for ev in merged["traceEvents"]}
            assert pids == {1, 2}
            keyed = {(ev["args"]["quorum_id"], ev["args"]["epoch"],
                      ev["args"]["step"])
                     for ev in merged["traceEvents"]
                     if ev.get("ph") == "X"}
            assert any(k[2] == RESET_STEP for k in keyed)
        finally:
            for m in managers.values():
                m.shutdown()
