"""Multi-replica-group integration tests on one host.

The reference's key trick (/root/reference/torchft/manager_integ_test.py):
each replica group is a *thread* in one process, the lighthouse is embedded,
groups talk over localhost TCP, failures are injected deterministically, and
the oracle is bitwise equality of final parameter pytrees across groups.
"""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchft_tpu import HostCommunicator, Lighthouse, Manager
from torchft_tpu.data import DistributedSampler
from torchft_tpu.models import MLP
from torchft_tpu.parallel import FTTrainer
from torchft_tpu.retry import RetryPolicy


class InjectedFailure(Exception):
    pass


class FailureInjector:
    """Deterministic failure injection (reference manager_integ_test.py:33-47)."""

    def __init__(self) -> None:
        self._failures = set()
        self.count = 0
        self._lock = threading.Lock()

    def fail_at(self, step: int) -> "FailureInjector":
        with self._lock:
            self._failures.add(step)
        return self

    def check(self, step: int) -> None:
        with self._lock:
            if step in self._failures:
                self._failures.remove(step)
                self.count += 1
                raise InjectedFailure(f"injected failure at step {step}")


def make_data(seed: int = 0, n: int = 64):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32)
    return x, y


def run_group(
    group: int,
    num_groups: int,
    lighthouse_addr: str,
    total_steps: int,
    injector: FailureInjector,
    min_replica_size: int = 1,
    attempts: int = 3,
    comm_factory=None,
):
    """One replica group's training job, restarted on injected crashes
    (reference worker_manager retry, manager_integ_test.py:50-68)."""
    x, y = make_data()
    model = MLP(features=(16,), num_classes=2)

    def loss_fn(params, batch):
        logits = model.apply(params, batch["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()

    if comm_factory is None:
        comm_factory = lambda: HostCommunicator(timeout_sec=15)  # noqa: E731

    last_exc = None
    commits = []  # (step, quorum_id, num_participants) per committed step
    for attempt in range(attempts):
        params = model.init(jax.random.key(42), jnp.zeros((1, 8)))
        trainer = FTTrainer(
            loss_fn=loss_fn,
            tx=optax.sgd(0.05),
            params=params,
            manager_factory=lambda load, save: Manager(
                comm=comm_factory(),
                load_state_dict=load,
                state_dict=save,
                min_replica_size=min_replica_size,
                replica_id=f"group{group}",
                lighthouse_addr=lighthouse_addr,
                rank=0,
                world_size=1,
                timeout_ms=15_000,
                quorum_timeout_ms=15_000,
            ),
            jit_fwd=True,
        )
        try:
            sampler = DistributedSampler(
                len(x), group, num_groups, batch_size=8, seed=1)
            batches = iter([])
            while trainer.manager.current_step() < total_steps:
                try:
                    idx = next(batches)
                except StopIteration:
                    sampler.set_epoch(sampler.epoch + 1)
                    batches = iter(sampler)
                    idx = next(batches)
                injector.check(trainer.manager.current_step() + 1)
                _, committed = trainer.train_step({"x": x[idx], "y": y[idx]})
                if committed:
                    commits.append((trainer.manager.current_step(),
                                    trainer.manager.quorum_id(),
                                    trainer.manager.num_participants()))
            return {
                "params": jax.device_get(trainer.params),
                "step": trainer.manager.current_step(),
                "batches_committed": trainer.manager.batches_committed(),
                "commits": commits,
            }
        except InjectedFailure as e:
            last_exc = e
        finally:
            trainer.shutdown()
    raise RuntimeError(f"group {group} exhausted retries: {last_exc}")


@pytest.mark.integration
class TestLighthouseOutage:
    """The lighthouse is the control plane's one SPOF; a fault-tolerance
    framework must survive ITS death too (round-4 verdict missing #1 — the
    reference has no story at all, src/lighthouse.rs). Contract: while the
    lighthouse is down, groups stall bounded (steps abort via the latched
    quorum error, the fail-fast streak guard does NOT fire) and keep
    serving; a replacement lighthouse at the same address picks them up on
    their next quorum round with no process restarts, and training
    converges bit-identical across groups afterwards."""

    def test_outage_stalls_then_restart_resumes(self):
        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=2,
                        join_timeout_ms=1000, quorum_tick_ms=50)
        addr = lh.address()
        x, y = make_data()
        model = MLP(features=(16,), num_classes=2)

        def loss_fn(params, batch):
            logits = model.apply(params, batch["x"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["y"]).mean()

        total_steps = 10
        pause_at = 3
        state: dict = {}
        errors: list = []
        arrived = threading.Barrier(3, timeout=120)
        resume = threading.Event()  # set only after the lighthouse is dead

        def worker(group: int) -> None:
            params = model.init(jax.random.key(42), jnp.zeros((1, 8)))
            trainer = FTTrainer(
                loss_fn=loss_fn, tx=optax.sgd(0.05), params=params,
                manager_factory=lambda load, save: Manager(
                    comm=HostCommunicator(timeout_sec=10),
                    load_state_dict=load, state_dict=save,
                    min_replica_size=2, replica_id=f"lhx{group}",
                    lighthouse_addr=addr, rank=0, world_size=1,
                    # NB the RPC layer makes 2 attempts per call (rpc.cc
                    # reconnect+retry), so a quorum visibly fails only
                    # after 2x this timeout — the outage below must
                    # outlast that for the stall to be observable. The
                    # Python retry layer is pinned OFF: its backoff would
                    # stretch (or, once the replacement lighthouse is up,
                    # absorb) the per-step aborts this test asserts.
                    retry_policy=RetryPolicy(max_attempts=1),
                    timeout_ms=4000, quorum_timeout_ms=2000,
                    # The guard must not fire during a bounded outage: an
                    # operator replacing a lighthouse needs minutes, and
                    # crashing every group would turn a control-plane blip
                    # into a full-job restart.
                    max_consecutive_failures=50,
                ),
            )
            state[group] = trainer
            try:
                b = {"x": x[:16], "y": y[:16]}
                while trainer.manager.current_step() < total_steps:
                    if trainer.manager.current_step() == pause_at \
                            and not resume.is_set():
                        arrived.wait()  # park so the outage lands mid-run
                        resume.wait(timeout=120)
                    trainer.train_step(b)
                state[f"params{group}"] = jax.device_get(trainer.params)
                state[f"metrics{group}"] = trainer.manager.metrics()
            except Exception as e:  # noqa: BLE001
                errors.append((group, e))
            finally:
                trainer.shutdown()

        threads = [threading.Thread(target=worker, args=(g,))
                   for g in range(2)]
        for t in threads:
            t.start()
        try:
            # Phase 1: both groups reach pause_at together, then park.
            arrived.wait()
            # Phase 2: kill the lighthouse, release the workers INTO the
            # outage. Their next quorum rounds hit a dead address: steps
            # must abort (stall) without any exception escaping.
            lh.shutdown()
            resume.set()
            time.sleep(7.0)  # > 2 rpc attempts x quorum_timeout_ms
            assert not errors, f"group crashed during outage: {errors}"
            assert state[0].manager.current_step() < total_steps, \
                "training progressed without a lighthouse"
            # Phase 3: replacement lighthouse at the SAME address — the
            # managers' configured lighthouse_addr must just work again.
            lh = Lighthouse(bind=addr, min_replicas=2,
                            join_timeout_ms=1000, quorum_tick_ms=50)
        finally:
            resume.set()
            for t in threads:
                t.join(timeout=180)
            lh.shutdown()

        assert not errors, f"worker raised: {errors}"
        assert state[0].manager.current_step() >= total_steps
        # The outage was *observed* (steps aborted) yet absorbed: the
        # streak guard never escalated (no errors) and both groups
        # converged to bitwise-identical parameters afterwards.
        aborted = (state["metrics0"]["aborted_steps"]
                   + state["metrics1"]["aborted_steps"])
        assert aborted >= 1, (state["metrics0"], state["metrics1"])
        ref_leaves = jax.tree_util.tree_leaves(state["params0"])
        got_leaves = jax.tree_util.tree_leaves(state["params1"])
        for a, b in zip(ref_leaves, got_leaves):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


    def test_membership_change_across_replacement(self):
        """The nasty replacement case: a group dies DURING the outage, so
        the replacement lighthouse's first quorum has different membership
        than the survivor's last one. The survivor must detect the change
        (quorum ids are boot-time-seeded precisely so a replacement can
        never re-mint an old incarnation's id — lighthouse.h), reconfigure
        its ring away from the dead peer, and finish alone; a replayed
        quorum id would skip the reconfigure and wedge every collective on
        the dead member forever."""
        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1,
                        join_timeout_ms=1000, quorum_tick_ms=50)
        addr = lh.address()
        x, y = make_data()
        model = MLP(features=(16,), num_classes=2)

        def loss_fn(params, batch):
            logits = model.apply(params, batch["x"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["y"]).mean()

        total_steps = 10
        pause_at = 3
        state: dict = {}
        errors: list = []
        arrived = threading.Barrier(3, timeout=120)
        resume = threading.Event()
        stop1 = threading.Event()  # tells group 1 to die (mid-outage)

        def worker(group: int) -> None:
            params = model.init(jax.random.key(42), jnp.zeros((1, 8)))
            trainer = FTTrainer(
                loss_fn=loss_fn, tx=optax.sgd(0.05), params=params,
                manager_factory=lambda load, save: Manager(
                    comm=HostCommunicator(timeout_sec=6),
                    load_state_dict=load, state_dict=save,
                    min_replica_size=1, replica_id=f"lhm{group}",
                    lighthouse_addr=addr, rank=0, world_size=1,
                    # Raw transport timing (see the outage test above).
                    retry_policy=RetryPolicy(max_attempts=1),
                    timeout_ms=4000, quorum_timeout_ms=2000,
                    max_consecutive_failures=50,
                ),
            )
            state[group] = trainer
            try:
                b = {"x": x[:16], "y": y[:16]}
                while trainer.manager.current_step() < total_steps:
                    if group == 1 and stop1.is_set():
                        return  # dies mid-outage, farewell goes nowhere
                    if trainer.manager.current_step() == pause_at \
                            and not resume.is_set():
                        arrived.wait()
                        resume.wait(timeout=120)
                    trainer.train_step(b)
                state[f"metrics{group}"] = trainer.manager.metrics()
                state[f"qid{group}"] = trainer.manager.quorum_id()
            except Exception as e:  # noqa: BLE001
                errors.append((group, e))
            finally:
                trainer.shutdown()

        threads = [threading.Thread(target=worker, args=(g,))
                   for g in range(2)]
        for t in threads:
            t.start()
        try:
            arrived.wait()
            qid_before = state[0].manager.quorum_id()
            lh.shutdown()
            stop1.set()   # group 1 dies while the lighthouse is down
            resume.set()
            time.sleep(5.0)
            assert not errors, f"crash during outage: {errors}"
            lh = Lighthouse(bind=addr, min_replicas=1,
                            join_timeout_ms=1000, quorum_tick_ms=50)
        finally:
            resume.set()
            stop1.set()
            for t in threads:
                t.join(timeout=180)
            lh.shutdown()

        assert not errors, f"worker raised: {errors}"
        mx = state["metrics0"]
        # Survivor finished alone: the replacement's quorum id differed
        # from the dead incarnation's, forcing the ring reconfigure away
        # from the dead peer (>= 2 reconfigures: initial + post-outage).
        assert state["qid0"] != qid_before
        assert mx["reconfigure_count"] >= 2, mx
        assert mx["committed_steps"] >= total_steps, mx


@pytest.mark.integration
class TestIntegration:
    def test_two_groups_converge(self):
        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=2,
                        join_timeout_ms=1000, quorum_tick_ms=50)
        try:
            with ThreadPoolExecutor(max_workers=2) as pool:
                futs = [
                    pool.submit(run_group, g, 2, lh.address(), 4,
                                FailureInjector(), 2)
                    for g in range(2)
                ]
                results = [f.result(timeout=120) for f in futs]
        finally:
            lh.shutdown()
        assert results[0]["step"] == results[1]["step"] == 4
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(a, b),
            results[0]["params"], results[1]["params"])

    def test_replica_death_and_recovery(self):
        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1,
                        join_timeout_ms=1000, quorum_tick_ms=50)
        injector = FailureInjector().fail_at(3)
        try:
            with ThreadPoolExecutor(max_workers=2) as pool:
                futs = [
                    pool.submit(run_group, 0, 2, lh.address(), 6,
                                FailureInjector(), 1),
                    pool.submit(run_group, 1, 2, lh.address(), 6,
                                injector, 1),
                ]
                results = [f.result(timeout=180) for f in futs]
        finally:
            lh.shutdown()
        assert injector.count == 1, "failure was not injected"
        assert results[0]["step"] == results[1]["step"] == 6
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(a, b),
            results[0]["params"], results[1]["params"])


@pytest.mark.integration
class TestSparesIntegration:
    """FIXED_WITH_SPARES end to end: three groups, participating world
    clamped to two — the third runs as a warm spare (computes, contributes
    zeros, excluded from 1/n) yet stays bitwise-identical, so promotion
    on a real death is instant."""

    def test_spare_tracks_but_does_not_contribute(self):
        from torchft_tpu.manager import WorldSizeMode

        n_groups, total = 3, 4
        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=n_groups,
                        join_timeout_ms=2000, quorum_tick_ms=20)
        x, y = make_data()
        model = MLP(features=(16,), num_classes=2)

        def loss_fn(params, batch):
            logits = model.apply(params, batch["x"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["y"]).mean()

        def run(group):
            params = model.init(jax.random.key(42), jnp.zeros((1, 8)))
            trainer = FTTrainer(
                loss_fn=loss_fn, tx=optax.sgd(0.05), params=params,
                manager_factory=lambda load, save: Manager(
                    comm=HostCommunicator(timeout_sec=15),
                    load_state_dict=load, state_dict=save,
                    min_replica_size=2,
                    world_size_mode=WorldSizeMode.FIXED_WITH_SPARES,
                    replica_id=f"spare{group}",
                    lighthouse_addr=lh.address(), rank=0, world_size=1,
                    timeout_ms=15_000, quorum_timeout_ms=15_000,
                ),
            )
            participants_seen = set()
            b = {"x": x[:16], "y": y[:16]}
            try:
                while trainer.manager.current_step() < total:
                    trainer.train_step(b)
                    participants_seen.add(
                        trainer.manager.num_participants())
                return (jax.device_get(trainer.params), participants_seen,
                        trainer.manager.is_participating())
            finally:
                trainer.shutdown()

        try:
            with ThreadPoolExecutor(max_workers=n_groups) as pool:
                futs = [pool.submit(run, g) for g in range(n_groups)]
                results = [f.result(timeout=180) for f in futs]
        finally:
            lh.shutdown()

        # arithmetic world stayed clamped at 2 for everyone
        for _, seen, _ in results:
            assert seen == {2}, seen
        # exactly one group ended as the non-participating spare
        assert sum(0 if p else 1 for _, _, p in results) == 1
        # spare included: identical params (it applies the same averaged
        # update — that's what makes instant promotion safe)
        for other in results[1:]:
            jax.tree_util.tree_map(
                lambda a, b_: np.testing.assert_array_equal(a, b_),
                results[0][0], other[0])


@pytest.mark.integration
class TestChaosSoak:
    """Randomized multi-failure soak: three replica groups, each killed at
    pseudo-random steps (seeded — the schedule is deterministic across
    runs), restarted, rejoined, healed. Broader than the reference's
    single-failure recovery test: failures overlap, quorums churn
    repeatedly, and every transition must preserve the lockstep invariant.
    Oracle: all groups reach the target step with bitwise-equal params."""

    def test_three_groups_random_failures(self):
        n_groups, total = 3, 20
        rng = np.random.default_rng(7)
        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=2,
                        join_timeout_ms=1000, quorum_tick_ms=50)
        injectors = []
        for g in range(n_groups):
            inj = FailureInjector()
            # Two failures per group somewhere in the middle; the cushion
            # before `total` keeps peers alive long enough for the last
            # restart to rejoin (min_replicas=2 would otherwise strand it).
            for s in rng.choice(np.arange(3, total - 5), size=2,
                                replace=False):
                inj.fail_at(int(s))
            injectors.append(inj)

        try:
            with ThreadPoolExecutor(max_workers=n_groups) as pool:
                futs = [
                    pool.submit(run_group, g, n_groups, lh.address(), total,
                                injectors[g], 2, 8)
                    for g in range(n_groups)
                ]
                results = [f.result(timeout=300) for f in futs]
        finally:
            lh.shutdown()

        assert all(r["step"] == total for r in results)
        # Each group's first scheduled failure always fires (a group can
        # only skip a failure step by healing past it, which requires an
        # earlier death). Later ones may be jumped over by a heal.
        assert all(inj.count >= 1 for inj in injectors)
        assert sum(inj.count for inj in injectors) >= n_groups + 1
        # No split brain, ever: a step committed by more than one group must
        # have been committed under ONE quorum. Two groups committing the
        # same step under different quorum ids means the lighthouse cut
        # disjoint quorums from overlapping liveness epochs (the regrow race
        # the joining-beat grace closes, _core/lighthouse.cc) — each side
        # would apply a divergent update at the same max_step, which no heal
        # can reconcile.
        step_qids: dict = {}
        for r in results:
            for step, qid, _ in r["commits"]:
                step_qids.setdefault(step, set()).add(qid)
        split = {s: q for s, q in step_qids.items() if len(q) > 1}
        assert not split, f"steps committed under multiple quorums: {split}"
        for other in results[1:]:
            jax.tree_util.tree_map(
                lambda a, b: np.testing.assert_array_equal(a, b),
                results[0]["params"], other["params"])

    @pytest.mark.nightly
    @pytest.mark.parametrize("seed", [11, 23, 47])
    def test_nightly_four_groups_heavy_churn(self, seed):
        """Nightly-scale soak (excluded from the per-commit budget): four
        groups, heavy churn, a long horizon, several seeds. Same oracles —
        lockstep params, no step committed under two quorums.

        Group 0 is immortal: the strict no-recommit oracle requires an
        unbroken max-step lineage. If every newest-state holder dies at
        once, the survivors legitimately REWIND and re-commit those steps
        under later quorums (replication-based FT loses what all replicas
        of the newest state held — reference semantics too), which is
        indistinguishable from split-brain in the (step, quorum_id) trace.
        One never-dying group pins the lineage so any multi-quorum step in
        the trace is a real protocol violation. (Observed: seed 11 with
        all-mortal groups produces exactly the legitimate-rewind trace.)

        The grace cap must exceed the worst-case step stall: a wedged-but-
        alive max-step holder (e.g. a multi-second jit compile on a
        contended CI core) that outlives heartbeat_grace_factor *
        join_timeout_ms is CUT, and the behind-members' re-commits then
        look like the rewind trace with the lineage still alive (observed
        once at the 4s default under 3 back-to-back soaks on one core).
        Same rule as production: grace > max stall."""
        n_groups, total = 4, 40
        rng = np.random.default_rng(seed)
        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=2,
                        join_timeout_ms=1000, quorum_tick_ms=50,
                        heartbeat_grace_factor=30)
        injectors = [FailureInjector()]  # group 0: immortal
        for g in range(1, n_groups):
            inj = FailureInjector()
            for s in rng.choice(np.arange(3, total - 8), size=4,
                                replace=False):
                inj.fail_at(int(s))
            injectors.append(inj)

        try:
            with ThreadPoolExecutor(max_workers=n_groups) as pool:
                futs = [
                    pool.submit(run_group, g, n_groups, lh.address(), total,
                                injectors[g], 2, 8)
                    for g in range(n_groups)
                ]
                results = [f.result(timeout=600) for f in futs]
        finally:
            lh.shutdown()

        assert all(r["step"] == total for r in results)
        step_qids: dict = {}
        for r in results:
            for step, qid, _ in r["commits"]:
                step_qids.setdefault(step, set()).add(qid)
        split = {s: q for s, q in step_qids.items() if len(q) > 1}
        assert not split, f"steps committed under multiple quorums: {split}"
        for other in results[1:]:
            jax.tree_util.tree_map(
                lambda a, b: np.testing.assert_array_equal(a, b),
                results[0]["params"], other["params"])


@pytest.mark.integration
class TestMeshIntegration:
    """Same oracles as TestIntegration but over the on-device
    MeshCommunicator (backends/mesh.py): full membership rides the jitted
    on-device sum, a death drops to the host fallback, and a rejoin
    returns to the mesh path — the Gloo/NCCL-style duality, per quorum."""

    def test_two_groups_converge_on_device(self):
        from torchft_tpu import MeshCommunicator, MeshWorld

        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=2,
                        join_timeout_ms=1000, quorum_tick_ms=50)
        world = MeshWorld(num_groups=2, timeout_sec=30)
        comms = []

        def factory():
            c = MeshCommunicator(world, group_index=len(comms))
            comms.append(c)
            return c

        try:
            with ThreadPoolExecutor(max_workers=2) as pool:
                futs = [
                    pool.submit(run_group, g, 2, lh.address(), 4,
                                FailureInjector(), 2, 3, factory)
                    for g in range(2)
                ]
                results = [f.result(timeout=120) for f in futs]
        finally:
            lh.shutdown()
        assert results[0]["step"] == results[1]["step"] == 4
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(a, b),
            results[0]["params"], results[1]["params"])
        # Full membership throughout: every communicator stayed on device.
        assert all(c.mode() == "mesh" for c in comms)

    def test_death_falls_back_then_returns_to_mesh(self):
        """One group dies and stays down past the join timeout, so the
        survivor's quorum shrinks below full membership (host fallback);
        the restart rejoins, heals, and full membership restores the
        on-device path. Coordination is deterministic: the victim sets the
        shared stop step after its first post-recovery commit, and the
        lockstep quorums carry both groups to that exact step."""
        from torchft_tpu import MeshCommunicator, MeshWorld

        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1,
                        join_timeout_ms=300, quorum_tick_ms=50)
        world = MeshWorld(num_groups=2, timeout_sec=30)
        modes_seen = []
        lock = threading.Lock()
        stop_at: dict = {}

        class RecordingMesh(MeshCommunicator):
            def configure(self, store_addr, rank, world_size):
                super().configure(store_addr, rank, world_size)
                with lock:
                    modes_seen.append(self.mode())

        x, y = make_data()
        model = MLP(features=(16,), num_classes=2)

        def loss_fn(params, batch):
            logits = model.apply(params, batch["x"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["y"]).mean()

        def make_trainer(gid):
            params = model.init(jax.random.key(42), jnp.zeros((1, 8)))
            return FTTrainer(
                loss_fn=loss_fn, tx=optax.sgd(0.05), params=params,
                manager_factory=lambda load, save: Manager(
                    comm=RecordingMesh(world), load_state_dict=load,
                    state_dict=save, min_replica_size=1, replica_id=gid,
                    lighthouse_addr=lh.address(), rank=0, world_size=1,
                    timeout_ms=15_000, quorum_timeout_ms=15_000,
                ),
            )

        b = {"x": x[:16], "y": y[:16]}

        deadline = time.monotonic() + 120  # bailout: hang -> failure, not wedge

        def survivor():
            trainer = make_trainer("mA")
            try:
                while ("step" not in stop_at
                       or trainer.manager.current_step() < stop_at["step"]):
                    if time.monotonic() > deadline:
                        raise TimeoutError("victim never set the stop step")
                    trainer.train_step(b)
                return jax.device_get(trainer.params), \
                    trainer.manager.current_step()
            finally:
                trainer.shutdown()

        def victim():
            try:
                trainer = make_trainer("mB")
                try:
                    while trainer.manager.current_step() < 3:
                        trainer.train_step(b)
                finally:
                    trainer.shutdown()  # death
                time.sleep(1.5)  # stay dead past the join timeout
                trainer = make_trainer("mB")  # slow restart, fresh member
                try:
                    # Recovery means the MERGED quorum: with min_replicas=1
                    # the lighthouse may transiently cut a solo {mB} quorum
                    # (straggler timeout races the survivor's call), which
                    # always re-merges via fast quorum — so step until a
                    # committed step saw both groups participating.
                    while True:
                        if time.monotonic() > deadline:
                            raise TimeoutError("victim never recovered")
                        _, committed = trainer.train_step(b)
                        if committed and trainer.manager.num_participants() == 2:
                            break
                    # Recovered: both groups are now in lockstep — run a
                    # few more joint steps and stop together.
                    stop_at["step"] = trainer.manager.current_step() + 3
                    while trainer.manager.current_step() < stop_at["step"]:
                        trainer.train_step(b)
                    return jax.device_get(trainer.params), \
                        trainer.manager.current_step()
                finally:
                    trainer.shutdown()
            except BaseException:
                # Unblock the survivor before surfacing the failure.
                stop_at.setdefault("step", -1)
                raise

        try:
            with ThreadPoolExecutor(max_workers=2) as pool:
                futs = [pool.submit(survivor), pool.submit(victim)]
                (p0, s0), (p1, s1) = [f.result(timeout=180) for f in futs]
        finally:
            lh.shutdown()
        assert s0 == s1 == stop_at["step"]
        jax.tree_util.tree_map(
            lambda a, b_: np.testing.assert_array_equal(a, b_), p0, p1)
        # The death shrank the quorum below full membership (host mode);
        # the rejoin restored it (mesh mode) — both transitions must have
        # happened.
        assert "host" in modes_seen and "mesh" in modes_seen
        assert modes_seen[-1] == "mesh"


@pytest.mark.integration
class TestFourGroupMesh:
    """BASELINE config 2's shape at test scale: 4 replica groups, each
    owning a 2-device fsdp sub-mesh of the 8-device host, cross-group
    gradients on the on-device MeshCommunicator, ResNet-style conv model.
    All groups must converge bitwise-identically."""

    def test_four_groups_sharded_converge(self):
        from jax.sharding import NamedSharding

        from torchft_tpu import MeshCommunicator, MeshWorld
        from torchft_tpu.models import ResNet
        from torchft_tpu.models.resnet import ResNetBlock
        from torchft_tpu.parallel import batch_spec, infer_fsdp_sharding, \
            make_mesh

        n_groups, total = 4, 3
        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=n_groups,
                        join_timeout_ms=2000, quorum_tick_ms=20)
        world = MeshWorld(num_groups=n_groups, timeout_sec=60)
        devs = jax.devices()
        assert len(devs) >= 8
        # micro-ResNet: the ResNet-50 family's machinery (stem, stages,
        # batch norm state) at test size
        model = ResNet(stage_sizes=(1, 1), block_cls=ResNetBlock,
                       num_classes=4, num_filters=8)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(32, 16, 16, 3)).astype(np.float32)
        y = rng.integers(0, 4, size=(32,)).astype(np.int32)

        def loss_fn(params, model_state, batch):
            logits, new_state = model.apply(
                {"params": params, **model_state}, batch["x"], train=True,
                mutable=["batch_stats"])
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["y"]).mean()
            return loss, new_state

        def run_group(g):
            mesh = make_mesh({"fsdp": 2}, devices=devs[2 * g: 2 * g + 2])
            variables = model.init(jax.random.key(3),
                                   jnp.zeros((1, 16, 16, 3)), train=True)
            trainer = FTTrainer(
                loss_fn=loss_fn,
                tx=optax.sgd(0.05),
                params=variables["params"],
                model_state={"batch_stats": variables["batch_stats"]},
                param_shardings=infer_fsdp_sharding(
                    variables["params"], mesh, min_size=64),
                batch_sharding=NamedSharding(
                    mesh, batch_spec(mesh, data_axes=("fsdp",))),
                manager_factory=lambda load, save: Manager(
                    comm=MeshCommunicator(world, group_index=g),
                    load_state_dict=load, state_dict=save,
                    min_replica_size=n_groups, replica_id=f"m4_{g}",
                    lighthouse_addr=lh.address(), rank=0, world_size=1,
                    # Generous: four groups jit-compile concurrently on
                    # one CPU core before their first join; under full-
                    # suite load the slowest straggler can exceed 20s and
                    # the early joiners' parked quorum RPCs must outlive
                    # it (observed flake at 20s).
                    timeout_ms=60_000, quorum_timeout_ms=60_000,
                ),
            )
            try:
                sampler = DistributedSampler(len(x), g, n_groups,
                                             batch_size=8, seed=1)
                batches = iter([])
                while trainer.manager.current_step() < total:
                    try:
                        idx = next(batches)
                    except StopIteration:
                        sampler.set_epoch(sampler.epoch + 1)
                        batches = iter(sampler)
                        idx = next(batches)
                    trainer.train_step({"x": x[idx], "y": y[idx]})
                return jax.device_get(trainer.params)
            finally:
                trainer.shutdown()

        try:
            with ThreadPoolExecutor(max_workers=n_groups) as pool:
                futs = [pool.submit(run_group, g) for g in range(n_groups)]
                # generous: 4 threads x jit compiles contend for one core
                results = [f.result(timeout=420) for f in futs]
        finally:
            lh.shutdown()
        # Params replicate bitwise; batch-norm running stats are local by
        # design (they track each group's own data shard, as in unsynced
        # BN under torch DDP) and are deliberately not compared.
        for other in results[1:]:
            jax.tree_util.tree_map(
                lambda a, b: np.testing.assert_array_equal(a, b),
                results[0], other)


@pytest.mark.integration
class TestHSDPIntegration:
    """HSDP: FSDP-sharded params inside each replica group + FT replication
    across groups (BASELINE.md config 3's shape), including healing of
    *sharded* arrays via device_put with the healer's shardings."""

    def test_sharded_death_and_recovery(self):
        from torchft_tpu.parallel import (
            batch_spec, infer_fsdp_sharding, make_mesh)
        from jax.sharding import NamedSharding

        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1,
                        join_timeout_ms=1000, quorum_tick_ms=50)
        x, y = make_data()
        model = MLP(features=(64,), num_classes=2)
        mesh = make_mesh({"fsdp": 8})

        def loss_fn(params, batch):
            logits = model.apply(params, batch["x"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["y"]).mean()

        def run_group(group, injector):
            last = None
            for attempt in range(3):
                params = model.init(jax.random.key(7), jnp.zeros((1, 8)))
                shardings = infer_fsdp_sharding(params, mesh, min_size=64)
                trainer = FTTrainer(
                    loss_fn=loss_fn,
                    # adamw, not sgd: its step counter is a leaf optax
                    # creates from scratch (not zeros_like(params)), the
                    # case where healed state must land on the mesh and
                    # not get pinned to one device (step.py _on_mesh).
                    tx=optax.adamw(0.05),
                    params=params,
                    param_shardings=shardings,
                    batch_sharding=NamedSharding(
                        mesh, batch_spec(mesh, data_axes=("fsdp",))),
                    manager_factory=lambda load, save: Manager(
                        comm=HostCommunicator(timeout_sec=15),
                        load_state_dict=load,
                        state_dict=save,
                        min_replica_size=1,
                        replica_id=f"hsdp{group}",
                        lighthouse_addr=lh.address(),
                        rank=0, world_size=1,
                        timeout_ms=15_000, quorum_timeout_ms=15_000,
                    ),
                )
                try:
                    sampler = DistributedSampler(len(x), group, 2,
                                                 batch_size=8, seed=1)
                    batches = iter([])
                    while trainer.manager.current_step() < 5:
                        try:
                            idx = next(batches)
                        except StopIteration:
                            sampler.set_epoch(sampler.epoch + 1)
                            batches = iter(sampler)
                            idx = next(batches)
                        injector.check(trainer.manager.current_step() + 1)
                        trainer.train_step({"x": x[idx], "y": y[idx]})
                    # params still sharded after train/heal
                    leaf = trainer.params["params"]["Dense_0"]["kernel"]
                    assert "fsdp" in str(leaf.sharding.spec)
                    return jax.device_get(trainer.params)
                except InjectedFailure as e:
                    last = e
                finally:
                    trainer.shutdown()
            raise RuntimeError(f"group {group} exhausted retries: {last}")

        injector = FailureInjector().fail_at(3)
        try:
            with ThreadPoolExecutor(max_workers=2) as pool:
                futs = [pool.submit(run_group, 0, FailureInjector()),
                        pool.submit(run_group, 1, injector)]
                results = [f.result(timeout=180) for f in futs]
        finally:
            lh.shutdown()
        assert injector.count == 1
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(a, b),
            results[0], results[1])


@pytest.mark.integration
class TestPipelineHeal:
    """FT x pipeline parallelism, end-to-end (round-4 verdict missing #2:
    'parallelism x FT compose' was an inference, not a test). Each replica
    group trains the transformer with its decoder layers STACKED
    ``[pp, L/pp, ...]`` and sharded over a pp axis of the group's own
    sub-mesh (parallel/pipeline.py); one group is killed and its restart
    must heal the stacked, pp-sharded layout from the survivor through
    ``serialization.device_put_like`` — the oracle is bitwise equality of
    the full pytree (stacked layers included) across groups afterwards."""

    def test_pp_stacked_death_and_recovery(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from torchft_tpu.models import (Transformer, TransformerConfig,
                                        causal_lm_loss)
        from torchft_tpu.models.transformer import DecoderLayer, RMSNorm
        from torchft_tpu.parallel import make_mesh
        from torchft_tpu.parallel.pipeline import (pipeline_apply,
                                                   pipeline_spec,
                                                   stack_layer_params)

        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1,
                        join_timeout_ms=1000, quorum_tick_ms=50)
        devs = jax.devices()
        assert len(devs) >= 8
        cfg = TransformerConfig(vocab_size=64, num_layers=2, embed_dim=32,
                                num_heads=2, hidden_dim=64, max_seq_len=16,
                                dtype=jnp.float32)
        model = Transformer(cfg)
        rng = np.random.default_rng(0)
        toks = rng.integers(0, 64, size=(64, 16)).astype(np.int32)
        n_micro = 2
        layer = DecoderLayer(cfg)

        def make_loss_fn(mesh):
            def loss_fn(tree, batch):
                t = batch["tokens"]
                rest = tree["rest"]
                x = rest["embed"]["embedding"][t].astype(cfg.dtype)

                def stage_fn(stage_params, h):
                    positions = jnp.broadcast_to(jnp.arange(h.shape[1]),
                                                 h.shape[:2])

                    def one_layer(h, lp):
                        return layer.apply({"params": lp}, h,
                                           positions), None

                    h, _ = jax.lax.scan(one_layer, h, stage_params)
                    return h

                x = pipeline_apply(stage_fn, tree["stacked"], x, n_micro,
                                   mesh)
                x = RMSNorm().apply({"params": rest["final_norm"]}, x)
                logits = jnp.einsum("bsd,dv->bsv", x.astype(jnp.float32),
                                    rest["lm_head"]["kernel"].astype(
                                        jnp.float32))
                return causal_lm_loss(logits, t)
            return loss_fn

        def run_group(group, injector):
            mesh = make_mesh({"pp": 2, "dp": 2},
                             devices=devs[4 * group: 4 * group + 4])
            loss_fn = make_loss_fn(mesh)
            last = None
            for attempt in range(3):
                params = model.init(jax.random.key(5),
                                    jnp.zeros((1, 16), jnp.int32))
                rest, stacked = stack_layer_params(params, cfg.num_layers,
                                                   pp=2)
                tree0 = {"rest": rest, "stacked": stacked}
                shardings = {
                    "rest": jax.tree_util.tree_map(
                        lambda _: NamedSharding(mesh, P()), rest),
                    "stacked": pipeline_spec(stacked, mesh),
                }
                trainer = FTTrainer(
                    loss_fn=loss_fn, tx=optax.sgd(0.05), params=tree0,
                    param_shardings=shardings,
                    batch_sharding={
                        "tokens": NamedSharding(mesh, P("dp"))},
                    manager_factory=lambda load, save: Manager(
                        comm=HostCommunicator(timeout_sec=15),
                        load_state_dict=load, state_dict=save,
                        # min 2: the survivor must NOT commit solo while
                        # the victim recompiles its pipeline (tens of
                        # seconds on a loaded 1-core box) — with min 1 it
                        # can finish and shut down first, the restart then
                        # forms a fresh singleton quorum and never heals
                        # (observed). Lockstep keeps the heal on the path
                        # under test and the final-step comparison exact.
                        min_replica_size=2, replica_id=f"pph{group}",
                        lighthouse_addr=lh.address(), rank=0, world_size=1,
                        timeout_ms=15_000, quorum_timeout_ms=15_000,
                    ),
                )
                try:
                    sampler = DistributedSampler(len(toks), group, 2,
                                                 batch_size=8, seed=1)
                    batches = iter([])
                    while trainer.manager.current_step() < 5:
                        try:
                            idx = next(batches)
                        except StopIteration:
                            sampler.set_epoch(sampler.epoch + 1)
                            batches = iter(sampler)
                            idx = next(batches)
                        injector.check(trainer.manager.current_step() + 1)
                        trainer.train_step({"tokens": toks[idx]})
                    # stacked layers still pp-sharded after train + heal
                    leaf = trainer.params["stacked"]["attn_norm"]["scale"]
                    assert "pp" in str(leaf.sharding.spec), leaf.sharding
                    assert leaf.shape[0] == 2  # [pp, L/pp, ...]
                    return jax.device_get(trainer.params)
                except InjectedFailure as e:
                    last = e
                finally:
                    trainer.shutdown()
            raise RuntimeError(f"group {group} exhausted retries: {last}")

        injector = FailureInjector().fail_at(3)
        try:
            with ThreadPoolExecutor(max_workers=2) as pool:
                futs = [pool.submit(run_group, 0, FailureInjector()),
                        pool.submit(run_group, 1, injector)]
                results = [f.result(timeout=240) for f in futs]
        finally:
            lh.shutdown()
        assert injector.count == 1  # the kill actually happened
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(a, b),
            results[0], results[1])


@pytest.mark.integration
class TestExpertParallelHeal:
    """FT x expert parallelism: each group trains the MoE transformer
    with expert stacks sharded over an ep axis of its own sub-mesh
    (models/moe.py ep_rules); one group is killed and its restart heals
    the expert-stacked, ep-sharded layout from the survivor. Companion to
    TestPipelineHeal — together they pin 'parallelism x FT compose' for
    both exotic tiers (round-4 verdict missing #2)."""

    def test_ep_sharded_death_and_recovery(self):
        from torchft_tpu.models import Transformer, TransformerConfig
        from torchft_tpu.models.moe import ep_rules
        from torchft_tpu.models.transformer import moe_lm_loss
        from torchft_tpu.parallel import make_mesh
        from torchft_tpu.parallel.sharding import combined_shardings
        from jax.sharding import NamedSharding, PartitionSpec as P

        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1,
                        join_timeout_ms=1000, quorum_tick_ms=50)
        devs = jax.devices()
        assert len(devs) >= 8
        cfg = TransformerConfig(vocab_size=64, num_layers=2, embed_dim=32,
                                num_heads=2, hidden_dim=64, max_seq_len=16,
                                dtype=jnp.float32, moe_experts=2)
        model = Transformer(cfg)
        rng = np.random.default_rng(0)
        toks = rng.integers(0, 64, size=(64, 16)).astype(np.int32)

        def loss_fn(params, batch):
            return moe_lm_loss(model, params, batch["tokens"])

        def run_group(group, injector):
            mesh = make_mesh({"ep": 2, "dp": 2},
                             devices=devs[4 * group: 4 * group + 4])
            last = None
            for attempt in range(3):
                params = model.init(jax.random.key(9),
                                    jnp.zeros((1, 16), jnp.int32))["params"]
                # min_size huge: ONLY the ep rules shard; everything else
                # replicates (the dryrun phase-4 layout).
                shardings = combined_shardings(
                    params, mesh, ep_rules(), min_size=1 << 30)
                trainer = FTTrainer(
                    loss_fn=loss_fn, tx=optax.sgd(0.05), params=params,
                    param_shardings=shardings,
                    batch_sharding={
                        "tokens": NamedSharding(mesh, P("dp"))},
                    manager_factory=lambda load, save: Manager(
                        comm=HostCommunicator(timeout_sec=15),
                        load_state_dict=load, state_dict=save,
                        # Lockstep (see TestPipelineHeal): the survivor
                        # must not finish while the victim recompiles.
                        min_replica_size=2, replica_id=f"eph{group}",
                        lighthouse_addr=lh.address(), rank=0, world_size=1,
                        timeout_ms=15_000, quorum_timeout_ms=15_000,
                    ),
                )
                try:
                    sampler = DistributedSampler(len(toks), group, 2,
                                                 batch_size=8, seed=1)
                    batches = iter([])
                    while trainer.manager.current_step() < 5:
                        try:
                            idx = next(batches)
                        except StopIteration:
                            sampler.set_epoch(sampler.epoch + 1)
                            batches = iter(sampler)
                            idx = next(batches)
                        injector.check(trainer.manager.current_step() + 1)
                        with mesh:
                            trainer.train_step({"tokens": toks[idx]})
                    # expert stacks still ep-sharded after train + heal
                    leaf = trainer.params["layer_0"]["moe"]["wi_gate"]
                    assert "ep" in str(leaf.sharding.spec), leaf.sharding
                    assert leaf.shape[0] == cfg.moe_experts
                    return jax.device_get(trainer.params)
                except InjectedFailure as e:
                    last = e
                finally:
                    trainer.shutdown()
            raise RuntimeError(f"group {group} exhausted retries: {last}")

        injector = FailureInjector().fail_at(3)
        try:
            with ThreadPoolExecutor(max_workers=2) as pool:
                futs = [pool.submit(run_group, 0, FailureInjector()),
                        pool.submit(run_group, 1, injector)]
                results = [f.result(timeout=240) for f in futs]
        finally:
            lh.shutdown()
        assert injector.count == 1
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(a, b),
            results[0], results[1])
