"""Multi-replica-group integration tests on one host.

The reference's key trick (/root/reference/torchft/manager_integ_test.py):
each replica group is a *thread* in one process, the lighthouse is embedded,
groups talk over localhost TCP, failures are injected deterministically, and
the oracle is bitwise equality of final parameter pytrees across groups.
"""

import threading
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchft_tpu import HostCommunicator, Lighthouse, Manager
from torchft_tpu.data import DistributedSampler
from torchft_tpu.models import MLP
from torchft_tpu.parallel import FTTrainer


class InjectedFailure(Exception):
    pass


class FailureInjector:
    """Deterministic failure injection (reference manager_integ_test.py:33-47)."""

    def __init__(self) -> None:
        self._failures = set()
        self.count = 0
        self._lock = threading.Lock()

    def fail_at(self, step: int) -> "FailureInjector":
        with self._lock:
            self._failures.add(step)
        return self

    def check(self, step: int) -> None:
        with self._lock:
            if step in self._failures:
                self._failures.remove(step)
                self.count += 1
                raise InjectedFailure(f"injected failure at step {step}")


def make_data(seed: int = 0, n: int = 64):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32)
    return x, y


def run_group(
    group: int,
    num_groups: int,
    lighthouse_addr: str,
    total_steps: int,
    injector: FailureInjector,
    min_replica_size: int = 1,
    attempts: int = 3,
):
    """One replica group's training job, restarted on injected crashes
    (reference worker_manager retry, manager_integ_test.py:50-68)."""
    x, y = make_data()
    model = MLP(features=(16,), num_classes=2)

    def loss_fn(params, batch):
        logits = model.apply(params, batch["x"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()

    last_exc = None
    for attempt in range(attempts):
        params = model.init(jax.random.key(42), jnp.zeros((1, 8)))
        trainer = FTTrainer(
            loss_fn=loss_fn,
            tx=optax.sgd(0.05),
            params=params,
            manager_factory=lambda load, save: Manager(
                comm=HostCommunicator(timeout_sec=15),
                load_state_dict=load,
                state_dict=save,
                min_replica_size=min_replica_size,
                replica_id=f"group{group}",
                lighthouse_addr=lighthouse_addr,
                rank=0,
                world_size=1,
                timeout_ms=15_000,
                quorum_timeout_ms=15_000,
            ),
            jit_fwd=True,
        )
        try:
            sampler = DistributedSampler(
                len(x), group, num_groups, batch_size=8, seed=1)
            batches = iter([])
            while trainer.manager.current_step() < total_steps:
                try:
                    idx = next(batches)
                except StopIteration:
                    sampler.set_epoch(sampler.epoch + 1)
                    batches = iter(sampler)
                    idx = next(batches)
                injector.check(trainer.manager.current_step() + 1)
                trainer.train_step({"x": x[idx], "y": y[idx]})
            return {
                "params": jax.device_get(trainer.params),
                "step": trainer.manager.current_step(),
                "batches_committed": trainer.manager.batches_committed(),
            }
        except InjectedFailure as e:
            last_exc = e
        finally:
            trainer.shutdown()
    raise RuntimeError(f"group {group} exhausted retries: {last_exc}")


@pytest.mark.integration
class TestIntegration:
    def test_two_groups_converge(self):
        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=2,
                        join_timeout_ms=1000, quorum_tick_ms=50)
        try:
            with ThreadPoolExecutor(max_workers=2) as pool:
                futs = [
                    pool.submit(run_group, g, 2, lh.address(), 4,
                                FailureInjector(), 2)
                    for g in range(2)
                ]
                results = [f.result(timeout=120) for f in futs]
        finally:
            lh.shutdown()
        assert results[0]["step"] == results[1]["step"] == 4
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(a, b),
            results[0]["params"], results[1]["params"])

    def test_replica_death_and_recovery(self):
        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1,
                        join_timeout_ms=1000, quorum_tick_ms=50)
        injector = FailureInjector().fail_at(3)
        try:
            with ThreadPoolExecutor(max_workers=2) as pool:
                futs = [
                    pool.submit(run_group, 0, 2, lh.address(), 6,
                                FailureInjector(), 1),
                    pool.submit(run_group, 1, 2, lh.address(), 6,
                                injector, 1),
                ]
                results = [f.result(timeout=180) for f in futs]
        finally:
            lh.shutdown()
        assert injector.count == 1, "failure was not injected"
        assert results[0]["step"] == results[1]["step"] == 6
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(a, b),
            results[0]["params"], results[1]["params"])


@pytest.mark.integration
class TestHSDPIntegration:
    """HSDP: FSDP-sharded params inside each replica group + FT replication
    across groups (BASELINE.md config 3's shape), including healing of
    *sharded* arrays via device_put with the healer's shardings."""

    def test_sharded_death_and_recovery(self):
        from torchft_tpu.parallel import (
            batch_spec, infer_fsdp_sharding, make_mesh)
        from jax.sharding import NamedSharding

        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1,
                        join_timeout_ms=1000, quorum_tick_ms=50)
        x, y = make_data()
        model = MLP(features=(64,), num_classes=2)
        mesh = make_mesh({"fsdp": 8})

        def loss_fn(params, batch):
            logits = model.apply(params, batch["x"])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["y"]).mean()

        def run_group(group, injector):
            last = None
            for attempt in range(3):
                params = model.init(jax.random.key(7), jnp.zeros((1, 8)))
                shardings = infer_fsdp_sharding(params, mesh, min_size=64)
                trainer = FTTrainer(
                    loss_fn=loss_fn,
                    tx=optax.sgd(0.05),
                    params=params,
                    param_shardings=shardings,
                    batch_sharding=NamedSharding(
                        mesh, batch_spec(mesh, data_axes=("fsdp",))),
                    manager_factory=lambda load, save: Manager(
                        comm=HostCommunicator(timeout_sec=15),
                        load_state_dict=load,
                        state_dict=save,
                        min_replica_size=1,
                        replica_id=f"hsdp{group}",
                        lighthouse_addr=lh.address(),
                        rank=0, world_size=1,
                        timeout_ms=15_000, quorum_timeout_ms=15_000,
                    ),
                )
                try:
                    sampler = DistributedSampler(len(x), group, 2,
                                                 batch_size=8, seed=1)
                    batches = iter([])
                    while trainer.manager.current_step() < 5:
                        try:
                            idx = next(batches)
                        except StopIteration:
                            sampler.set_epoch(sampler.epoch + 1)
                            batches = iter(sampler)
                            idx = next(batches)
                        injector.check(trainer.manager.current_step() + 1)
                        trainer.train_step({"x": x[idx], "y": y[idx]})
                    # params still sharded after train/heal
                    leaf = trainer.params["params"]["Dense_0"]["kernel"]
                    assert "fsdp" in str(leaf.sharding.spec)
                    return jax.device_get(trainer.params)
                except InjectedFailure as e:
                    last = e
                finally:
                    trainer.shutdown()
            raise RuntimeError(f"group {group} exhausted retries: {last}")

        injector = FailureInjector().fail_at(3)
        try:
            with ThreadPoolExecutor(max_workers=2) as pool:
                futs = [pool.submit(run_group, 0, FailureInjector()),
                        pool.submit(run_group, 1, injector)]
                results = [f.result(timeout=180) for f in futs]
        finally:
            lh.shutdown()
        assert injector.count == 1
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(a, b),
            results[0], results[1])
