"""Straggler-aware fleet rebalancing tests (ISSUE 19,
docs/design/fleet_rebalance.md).

Tier-1 (marker ``rebalance``, ``scripts/test.sh rebalance``), all
native-free: the fraction-table wire format, the pure-Python
Rebalancer ladder frozen boundary-for-boundary against the C++ mirror
(core_test.cc carries the SAME snapshot literals — a drift on either
side fails one of the two), the ladder's edge cases (duplicate-step
replay, sticky ineligible rows, farewell, small-fleet median
behavior, the boost cap's unallocated remainder), the Manager's
commit-boundary adoption protocol (uncoordinated hint fallback,
decider-publishes/all-adopt over a fake quorum store, refusal
classes, bounds clamping, the digest's in-force fraction stamp and
its TypeError compatibility ladder), the ElasticSampler's fractional
draws + fold-weight reporting, the chaos ``slow:`` band, and the
composed-fraction bitwise weighted fold over real socketpair rings.

The PhasedChaos stable -> storm -> stable shrink-then-restore soak
(the zero-flap acceptance gate) rides ``nightly``+``slow``.
"""

import threading
import time
from unittest.mock import MagicMock

import numpy as np
import pytest

import conftest  # noqa: F401 — repo-standard path/env setup
from torchft_tpu import chaos, fleet
from torchft_tpu._native import QuorumResult
from torchft_tpu.backends.host import HostCommunicator, _Ring
from torchft_tpu.communicator import DummyCommunicator
from torchft_tpu.data import ElasticSampler, _reports_samples
from torchft_tpu.manager import _REBALANCE_KEY, Manager

pytestmark = pytest.mark.rebalance


# --------------------------------------------------------------- helpers


def quorum_result(
    quorum_id=1,
    recover_manager_address="manager1:1234",
    store_address="",
    max_step=1,
    max_rank=0,
    max_world_size=2,
    replica_rank=0,
    replica_world_size=2,
    heal=False,
    rebalance_table="",
):
    return QuorumResult(
        quorum_id=quorum_id,
        recover_manager_address=recover_manager_address,
        store_address=store_address,
        max_step=max_step,
        max_rank=max_rank,
        max_world_size=max_world_size,
        replica_rank=replica_rank,
        replica_world_size=replica_world_size,
        heal=heal,
        rebalance_table=rebalance_table,
    )


def make_manager(client, comm=None, min_replica_size=1, **kwargs):
    return Manager(
        comm=comm or DummyCommunicator(),
        load_state_dict=kwargs.pop("load_state_dict", MagicMock()),
        state_dict=kwargs.pop("state_dict", lambda: {"w": np.ones(2)}),
        min_replica_size=min_replica_size,
        rank=0,
        world_size=1,
        replica_id=kwargs.pop("replica_id", "rebaltest"),
        rebalance=kwargs.pop("rebalance", True),
        _manager_client=client,
        **kwargs,
    )


def boundary(m, tree=None):
    """One scripted step/allreduce/vote boundary; returns the vote."""
    m.step()
    m.allreduce(tree if tree is not None
                else {"g": np.ones(4, np.float32)}).result()
    return m.should_commit()


class FakeStore:
    """Dict-backed stand-in for the native StoreClient, injectable via
    the Manager's per-address store-client cache (test_policy.py's
    coordination harness, reused for the rebalance key)."""

    def __init__(self):
        self.kv = {}
        self.lock = threading.Lock()

    def set(self, key, value):
        with self.lock:
            self.kv[key] = value if isinstance(value, bytes) \
                else str(value).encode()

    def get(self, key, timeout_ms=0):
        with self.lock:
            if key not in self.kv:
                raise KeyError(key)
            return self.kv[key]


class BrokenStore(FakeStore):
    """Publishes fine, every read fails — the torn-control-plane case:
    adoption must fall back to 'adopt nothing this boundary'."""

    def get(self, key, timeout_ms=0):
        raise RuntimeError("store read lost")


def weighted_oracle(xs, weights, dtype=np.float32):
    """The documented weighted-fold contract, spelled in single-process
    numpy: sum of w_r * x_r in rank order (zero-weight contributions
    EXCLUDED, not multiplied by zero), true-divided by the total."""
    dt = np.dtype(dtype)
    acc = np.zeros(np.ravel(xs[0]).size, dt)
    for w, x in zip(weights, xs):
        if w:
            acc += np.ravel(x).astype(dt) * dt.type(w)
    total = sum(weights)
    if total:
        acc /= dt.type(total)
    return acc


def _socketpair_rings(world):
    import socket as _socket

    pairs = [_socket.socketpair() for _ in range(world)]
    return [_Ring(pairs[r][0], pairs[(r - 1) % world][1],
                  _socket.socket())
            for r in range(world)]


def _run_ring(world, fn):
    rings = _socketpair_rings(world)
    comms = []
    for r in range(world):
        c = HostCommunicator(timeout_sec=15)
        c._rank, c._world = r, world
        comms.append(c)
    out = [None] * world
    errors = []

    def w(r):
        try:
            out[r] = fn(comms[r], rings[r], r)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    ts = [threading.Thread(target=w, args=(r,)) for r in range(world)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    alive = [t for t in ts if t.is_alive()]
    for ring in rings:
        ring.close()
    for c in comms:
        c.shutdown()
    assert not alive, "weighted ring deadlocked"
    return out, errors


# ----------------------------------------------------- table wire format


class TestRebalanceTable:
    def test_roundtrip_and_sorted_canonical_order(self):
        fr = {"zeta": 0.5, "alpha": 1.1667, "mid": 0.875}
        table = fleet.format_rebalance_table(fr)
        assert table == "alpha=1.1667,mid=0.8750,zeta=0.5000"
        back = fleet.parse_rebalance_table(table)
        assert back == {"alpha": 1.1667, "mid": 0.875, "zeta": 0.5}

    def test_uniform_entries_omitted_empty_means_uniform(self):
        assert fleet.format_rebalance_table({"a": 1.0, "b": 1.0}) == ""
        assert fleet.parse_rebalance_table("") == {}

    def test_malformed_entries_dropped_not_fatal(self):
        got = fleet.parse_rebalance_table(
            "a=0.7500,garbage,=0.5,b=notanumber,c=0.6250")
        assert got == {"a": 0.75, "c": 0.625}

    def test_out_of_ladder_fractions_dropped(self):
        # An old/corrupt table must never adopt past the skew bounds:
        # entries outside [FLOOR, CEIL] read as absent (-> 1.0).
        got = fleet.parse_rebalance_table("a=0.2500,b=2.0000,c=0.5000")
        assert got == {"c": 0.5}

    def test_rids_with_equals_sign_roundtrip(self):
        # rpartition: the LAST '=' splits, so exotic replica ids keep
        # working.
        table = fleet.format_rebalance_table({"grp=east": 0.75})
        assert fleet.parse_rebalance_table(table) == {"grp=east": 0.75}


# ------------------------------------------------- Rebalancer (mirror)


class TestRebalancerLadderParity:
    """The frozen shrink -> recover trace. core_test.cc's
    test_rebalancer_ladder_parity carries these EXACT snapshot
    literals: the C++ rebalancer and this pure-Python mirror must walk
    the same ladder boundary-for-boundary, or one of the two suites
    fails — the mirror-parity contract of the fleet plane."""

    # (boundary k, table, seq, shrinks_total, restores_total)
    SNAPS = [
        (1, "", 0, 0, 0),
        (3, "a=1.0417,b=1.0417,c=0.8750,d=1.0417", 1, 1, 0),
        (7, "a=1.0833,b=1.0833,c=0.7500,d=1.0833", 2, 2, 0),
        (11, "a=1.1250,b=1.1250,c=0.6250,d=1.1250", 3, 3, 0),
        (15, "a=1.1667,b=1.1667,c=0.5000,d=1.1667", 4, 4, 0),
        (21, "a=1.1250,b=1.1250,c=0.6250,d=1.1250", 5, 4, 1),
        (27, "a=1.0833,b=1.0833,c=0.7500,d=1.0833", 6, 4, 2),
        (33, "a=1.0417,b=1.0417,c=0.8750,d=1.0417", 7, 4, 3),
        (39, "", 8, 4, 4),
    ]

    def test_shrink_then_recover_trace_matches_cpp_mirror(self):
        rb = fleet.Rebalancer()
        base = {"a": 100.0, "b": 100.0, "c": 200.0, "d": 100.0}
        # reported_fraction trails the assigned table by one boundary
        # (the adoption lag real managers have) and the wall scales
        # with it (a shrunken batch finishes proportionally faster).
        prev = {rid: 1.0 for rid in base}
        snaps = iter(self.SNAPS)
        expect = next(snaps)
        for k in range(1, 40):
            if k == 16:
                base["c"] = 100.0  # the straggler recovers
            prev = rb.observe(
                [(rid, k, base[rid] * prev[rid], prev[rid], True)
                 for rid in sorted(base)])
            if expect is not None and expect[0] == k:
                assert (rb.table, rb.seq, rb.shrinks_total,
                        rb.restores_total) == expect[1:], f"k={k}"
                expect = next(snaps, None)
        assert expect is None  # every snapshot visited
        assert all(f == 1.0 for f in rb.fractions().values())

    def test_fleet_total_conserved_at_the_floor(self):
        """At the deepest snapshot (c at the 0.5 floor) the trimmed
        half-slice is exactly absorbed by the three headroom groups:
        the fleet sample total is conserved."""
        rb = fleet.Rebalancer()
        base = {"a": 100.0, "b": 100.0, "c": 200.0, "d": 100.0}
        prev = {rid: 1.0 for rid in base}
        for k in range(1, 16):
            prev = rb.observe(
                [(rid, k, base[rid] * prev[rid], prev[rid], True)
                 for rid in sorted(base)])
        fr = rb.fractions()
        assert fr["c"] == 0.5
        assert sum(fr.values()) == pytest.approx(4.0)
        assert all(f <= fleet.REBALANCE_CEIL + 1e-9
                   for f in fr.values())

    def test_floor_is_terminal_no_further_shrink(self):
        rb = fleet.Rebalancer()
        base = {"a": 100.0, "b": 100.0, "c": 200.0, "d": 100.0}
        prev = {rid: 1.0 for rid in base}
        for k in range(1, 40):  # never recovers
            prev = rb.observe(
                [(rid, k, base[rid] * prev[rid], prev[rid], True)
                 for rid in sorted(base)])
        assert rb.fractions()["c"] == fleet.REBALANCE_FLOOR
        assert rb.shrinks_total == 4  # 1.0 -> 0.5 in eighths, then stop
        # Still loud every boundary, but the floor latches: no flap.
        assert rb.restores_total == 0


class TestRebalancerEdges:
    def _rows(self, walls, step, elig=None):
        elig = elig or {}
        return [(rid, step, w, 1.0, elig.get(rid, True))
                for rid, w in sorted(walls.items())]

    def test_duplicate_step_replay_takes_no_observation(self):
        """Aggregate-recompute cadence (the 200 ms lighthouse cache, a
        dashboard poller) must not inflate the ladder clock: the same
        boundary replayed 10x never accumulates persistence."""
        rb = fleet.Rebalancer()
        walls = {"a": 100, "b": 100, "c": 400, "d": 100}
        for _ in range(10):
            rb.observe(self._rows(walls, step=1))
        assert rb.shrinks_total == 0 and rb.table == ""

    def test_ineligible_straggler_sticky_no_shrink_no_boost(self):
        """A healer/degraded row is legitimately slow: its slowness is
        explained, so the ladder freezes (sticky fraction) instead of
        shrinking it — and it never receives boost either."""
        rb = fleet.Rebalancer()
        walls = {"a": 100, "b": 100, "c": 400, "d": 100}
        for k in range(1, 9):
            rb.observe(self._rows(walls, step=k, elig={"c": False}))
        assert rb.shrinks_total == 0 and rb.table == ""
        assert rb.fractions()["c"] == 1.0

    def test_forget_drops_group_and_its_deficit(self):
        rb = fleet.Rebalancer()
        walls = {"a": 100, "b": 100, "c": 400, "d": 100}
        for k in range(1, 4):
            rb.observe(self._rows(walls, step=k))
        assert rb.shrinks_total == 1
        rb.forget("c")
        assert fleet.format_rebalance_table(rb.fractions()) == ""

    def test_departed_group_dropped_from_observation(self):
        """Absent from rows == departed: same as forget, driven by the
        aggregate view instead of the farewell RPC."""
        rb = fleet.Rebalancer()
        walls = {"a": 100, "b": 100, "c": 400, "d": 100}
        for k in range(1, 4):
            rb.observe(self._rows(walls, step=k))
        assert rb.shrinks_total == 1
        rb.observe(self._rows({"a": 100, "b": 100, "d": 100}, step=4))
        assert fleet.format_rebalance_table(rb.fractions()) == ""

    def test_two_group_fleet_median_absorbs_a_2x_outlier(self):
        """Pinned so nobody 'fixes' the median into a mean and changes
        small-fleet behavior silently: with 2 groups the outlier drags
        the median up (med 150, ratio 1.33 < HI), so a 2x straggler
        never shrinks — only past 3x does a 2-group outlier go loud."""
        rb = fleet.Rebalancer()
        for k in range(1, 13):
            rb.observe(self._rows({"a": 100, "b": 200}, step=k))
        assert rb.shrinks_total == 0 and rb.table == ""

    def test_two_group_fleet_4x_outlier_does_shrink(self):
        rb = fleet.Rebalancer()
        prev = {"a": 1.0, "b": 1.0}
        for k in range(1, 13):
            prev = rb.observe(
                [(rid, k, w * prev[rid], prev[rid], True)
                 for rid, w in (("a", 100.0), ("b", 400.0))])
        assert rb.shrinks_total >= 1
        assert rb.fractions()["b"] < 1.0
        assert rb.fractions()["a"] > 1.0  # the survivor absorbs

    def test_boost_cap_leaves_remainder_unallocated(self):
        """Two groups at the floor with a single headroom group: the
        1.0 deficit would boost it to 2.0, but the CEIL caps it at 1.5
        and the remainder goes UNALLOCATED — the fleet total shrinks
        rather than overloading the one fast group into the next
        straggler."""
        rb = fleet.Rebalancer()
        for rid in ("a", "b", "c"):
            st = rb._st(rid)
            st["eligible"] = True
        rb._st("a")["fraction"] = 0.5
        rb._st("b")["fraction"] = 0.5
        fr = rb.fractions()
        assert fr == {"a": 0.5, "b": 0.5, "c": 1.5}
        assert sum(fr.values()) == pytest.approx(2.5)  # not 3.0

    def test_seq_counts_table_changes_only(self):
        """seq is the flap counter: identical recomputes never bump."""
        rb = fleet.Rebalancer()
        walls = {"a": 100, "b": 100, "c": 400, "d": 100}
        for k in range(1, 3):
            rb.observe(self._rows(walls, step=k))
        assert rb.seq == 0  # loud but below persistence: no change yet
        rb.observe(self._rows(walls, step=3))
        assert rb.seq == 1  # the shrink landed
        rb.observe(self._rows(walls, step=4))
        assert rb.seq == 1  # cooldown: same table, no bump


# --------------------------------------------- Manager adoption protocol


class TestManagerAdoption:
    def test_disabled_by_default_fraction_inert(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result(
            rebalance_table="off=0.5000")
        client.should_commit.return_value = True
        m = make_manager(client, rebalance=False, replica_id="off")
        try:
            boundary(m)
            assert not m.rebalance_enabled()
            assert m.rebalance_fraction() == 1.0
            mx = m.metrics()
            assert mx["rebalance_fraction"] == 1.0
            assert mx["rebalance_adoptions_total"] == 0
        finally:
            m.shutdown()

    def test_device_array_comm_rejected_at_build(self):
        class _DeviceComm(DummyCommunicator):
            wants_device_arrays = True

        with pytest.raises(ValueError, match="host-path"):
            make_manager(MagicMock(), comm=_DeviceComm())

    def test_uncoordinated_hint_adoption_and_restore(self):
        """Single-group / storeless runs adopt straight from their own
        FleetHint table copy; an entry vanishing from the table is the
        restore-to-uniform spelling."""
        client = MagicMock()
        client.quorum.return_value = quorum_result(
            rebalance_table="rebaltest=0.7500")
        client.should_commit.return_value = True
        m = make_manager(client)
        try:
            boundary(m)
            assert m.rebalance_fraction() == 0.75
            assert m.participant_slot()[2] == 0.75
            mx = m.metrics()
            assert mx["rebalance_fraction"] == 0.75
            assert mx["rebalance_adoptions_total"] == 1
            # Absent from the table -> back to the uniform share.
            client.quorum.return_value = quorum_result(
                rebalance_table="")
            boundary(m)
            assert m.rebalance_fraction() == 1.0
            assert m.metrics()["rebalance_adoptions_total"] == 2
            events = [e["event"] for e in m.history()]
            assert events.count("rebalance_adopt") == 2
        finally:
            m.shutdown()

    def test_absent_table_field_is_inert_not_a_restore(self):
        """Tri-state hint: a pre-rebalance lighthouse (no table
        attribute at all) must never read as a restore-everyone order —
        the stored table only refreshes on a STRING."""
        client = MagicMock()
        client.quorum.return_value = quorum_result(
            rebalance_table="inert=0.7500")
        client.should_commit.return_value = True
        m = make_manager(client, replica_id="inert")
        try:
            boundary(m)
            assert m.rebalance_fraction() == 0.75
            q = quorum_result()
            q.rebalance_table = None  # duck-typed old control plane
            client.quorum.return_value = q
            boundary(m)
            assert m.rebalance_fraction() == 0.75  # sticky, no restore
        finally:
            m.shutdown()

    def test_refusal_defers_then_lands_next_boundary(self):
        """save_durable's refusal classes apply: an errored boundary
        counts rebalance_deferred_total and the retry lands at the next
        clean boundary (the table re-reads every round — nothing is
        lost)."""
        client = MagicMock()
        client.quorum.return_value = quorum_result(
            rebalance_table="defer=0.6250")
        client.should_commit.return_value = False
        m = make_manager(client, replica_id="defer")
        try:
            m.step()
            m.allreduce({"g": np.ones(4, np.float32)}).result()
            m.report_error(RuntimeError("injected step error"))
            m.should_commit()
            assert m.rebalance_fraction() == 1.0
            mx = m.metrics()
            assert mx["rebalance_deferred_total"] == 1
            assert mx["rebalance_adoptions_total"] == 0
            # The error clears at the next step(); adoption retries.
            client.should_commit.return_value = True
            boundary(m)
            assert m.rebalance_fraction() == 0.625
            assert m.metrics()["rebalance_adoptions_total"] == 1
            events = [e["event"] for e in m.history()]
            assert "rebalance_deferred" in events
        finally:
            m.shutdown()

    def test_out_of_bounds_entries_never_adopt(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result(
            rebalance_table="clamp=0.2500")  # below the floor
        client.should_commit.return_value = True
        m = make_manager(client, replica_id="clamp")
        try:
            boundary(m)
            assert m.rebalance_fraction() == 1.0
            assert m.metrics()["rebalance_adoptions_total"] == 0
        finally:
            m.shutdown()

    def _pair(self, store, decider_table):
        """Two coordinated managers over a fake quorum store — the
        policy-coordination harness with the rebalance key."""
        ms = []
        for rank in range(2):
            client = MagicMock()
            client.quorum.return_value = quorum_result(
                store_address="fake:0", max_rank=rank,
                replica_rank=rank,
                rebalance_table=decider_table if rank == 0 else "")
            client.should_commit.return_value = True
            m = make_manager(client,
                             comm=DummyCommunicator(world_size=2),
                             replica_id=f"reb{rank}")
            m._healset_store = ("fake:0", store)  # inject the fake
            ms.append(m)
        return ms

    def test_decider_publishes_follower_adopts(self):
        """The decider-publishes/all-adopt protocol: only the decider's
        lighthouse hint carries the table, yet the follower lands its
        own entry via the store read — cross-group lockstep without a
        new RPC."""
        store = FakeStore()
        ms = self._pair(store, decider_table="reb1=0.8750")
        try:
            for m in ms:
                boundary(m)
            assert store.kv[_REBALANCE_KEY] == b"1:reb1=0.8750"
            assert ms[1].rebalance_fraction() == 0.875
            # The decider itself is absent from the table: stays 1.0.
            assert ms[0].rebalance_fraction() == 1.0
        finally:
            for m in ms:
                m.shutdown()

    def test_follower_never_publishes(self):
        store = FakeStore()
        ms = self._pair(store, decider_table="reb1=0.8750")
        try:
            boundary(ms[1])  # follower first: nothing published yet
            assert _REBALANCE_KEY not in store.kv
            assert ms[1].rebalance_fraction() == 1.0  # read had no key
        finally:
            for m in ms:
                m.shutdown()

    def test_failed_read_adopts_nothing(self):
        """Stale-but-consistent beats a torn default: when the
        coordinated read fails, the boundary adopts NOTHING — not the
        local hint copy, not 1.0."""
        store = BrokenStore()
        ms = self._pair(store, decider_table="")
        try:
            # The follower's own hint says shrink; the coordinated read
            # is authoritative and it failed -> no adoption either way.
            ms[1]._client.quorum.return_value = quorum_result(
                store_address="fake:0", max_rank=1, replica_rank=1,
                rebalance_table="reb1=0.5000")
            for m in ms:
                boundary(m)
            assert ms[1].rebalance_fraction() == 1.0
            assert ms[1].metrics()["rebalance_adoptions_total"] == 0
        finally:
            for m in ms:
                m.shutdown()

    def test_composed_capacity_times_rebalance(self):
        """Degraded capacity and the rebalance share compose
        multiplicatively in the ONE atomic snapshot the sampler draws
        by, and the fallback wire weight encodes the same product."""
        client = MagicMock()
        client.quorum.return_value = quorum_result(
            rebalance_table="compose=0.7500")
        client.should_commit.return_value = True
        m = make_manager(client, replica_id="compose",
                         degraded_mode=True)
        try:
            boundary(m)
            assert m.request_degrade(0.5, reason="test")
            rank, _committed, frac = m.participant_slot()
            assert rank == 0
            assert frac == pytest.approx(0.375)
            assert m._wire_weight() == round(0.375 * 10_000)
            m.set_step_samples(24)  # the sampler's exact draw wins
            assert m._wire_weight() == 24
        finally:
            m.shutdown()

    def test_digest_stamps_in_force_fraction_one_boundary_lag(self):
        """The digest's rebalance_fraction is the fraction the measured
        step actually RAN under: an adoption at boundary k is stamped
        from boundary k+1 on — stamping the live value would
        mis-normalize the just-measured wall and flap the ladder."""

        class _Capture:
            def __init__(self):
                self.calls = []

            def set_status(self, *a, **k):
                pass

            def set_digest(self, **kw):
                self.calls.append(kw)

            def lighthouse_redials(self):  # metrics() reads this
                return 0

            def shutdown(self):
                pass

        client = MagicMock()
        client.quorum.return_value = quorum_result(rebalance_table="")
        client.should_commit.return_value = True
        m = make_manager(client, replica_id="digest",
                         fleet_telemetry=True)
        cap = _Capture()
        m._manager_server = cap
        try:
            boundary(m)  # first boundary: no wall to report yet
            client.quorum.return_value = quorum_result(
                rebalance_table="digest=0.7500")
            boundary(m)  # adoption lands AFTER this boundary's wall
            boundary(m)
            assert [c["rebalance_fraction"] for c in cap.calls] \
                == [1.0, 0.75]
            assert all(c["step"] >= 1 for c in cap.calls)
        finally:
            m.shutdown()

    def test_digest_typeerror_ladder_keeps_older_servers_working(self):
        """Control planes predating each digest field generation keep
        receiving digests: the TypeError retry ladder drops ram_peers
        first (still unplumbed in the C bridge), then the rebalance
        fraction, then attestation."""

        class _PreRam:
            def __init__(self):
                self.calls = []

            def set_status(self, *a, **k):
                pass

            def set_digest(self, **kw):
                if "ram_peers" in kw:
                    raise TypeError("unexpected ram_peers")
                self.calls.append(kw)

            def lighthouse_redials(self):  # metrics() reads this
                return 0

            def shutdown(self):
                pass

        class _PreRebalance(_PreRam):
            def set_digest(self, **kw):
                if "ram_peers" in kw or "rebalance_fraction" in kw:
                    raise TypeError("pre-rebalance server")
                self.calls.append(kw)

        for server, has_frac in ((_PreRam(), True),
                                 (_PreRebalance(), False)):
            client = MagicMock()
            client.quorum.return_value = quorum_result()
            client.should_commit.return_value = True
            m = make_manager(client, replica_id="ladder",
                             fleet_telemetry=True)
            m._manager_server = server
            try:
                boundary(m)
                boundary(m)
                assert server.calls, type(server).__name__
                assert ("rebalance_fraction" in server.calls[0]) \
                    == has_frac
                assert "state_digest" in server.calls[0]
            finally:
                m.shutdown()


# ------------------------------------------------ ElasticSampler draws


class _FakeSlot:
    """Duck-typed manager for the sampler: one atomic slot snapshot,
    recording every reported fold weight."""

    def __init__(self, rank=0, committed=0, frac=1.0, degraded=False):
        self.rank, self.committed, self.frac = rank, committed, frac
        self._degraded = degraded
        self.reported = []

    def participant_slot(self):
        return (self.rank, self.committed, self.frac)

    def set_step_samples(self, n):
        self.reported.append(n)

    def degraded_mode(self):
        return self._degraded


class TestSamplerFractions:
    def test_shrunken_draw_reports_weight_without_degraded_mode(self):
        """The ISSUE's decouple regression: a rebalance-shrunken draw
        (fraction < 1, degraded mode OFF) must still report its exact
        sample count — gating on the degraded probe alone would leave
        the fold weight silently at full batch."""
        mgr = _FakeSlot(frac=0.75, degraded=False)
        s = ElasticSampler(64, mgr, batch_size=8, seed=3)
        idx = s.next_indices()
        assert len(idx) == 6  # round(8 * 0.75)
        assert mgr.reported == [6]

    def test_full_fraction_outside_degraded_mode_skips_report(self):
        mgr = _FakeSlot(frac=1.0, degraded=False)
        s = ElasticSampler(64, mgr, batch_size=8)
        assert len(s.next_indices()) == 8
        assert mgr.reported == []

    def test_degraded_mode_full_draw_still_reports(self):
        mgr = _FakeSlot(frac=1.0, degraded=True)
        s = ElasticSampler(64, mgr, batch_size=8)
        s.next_indices()
        assert mgr.reported == [8]

    def test_boost_draws_into_neighbor_slot_prefix(self):
        """A boosted group (fraction > 1) absorbs the straggler's
        trimmed slice by drawing past its slot boundary: the overflow
        is exactly the NEXT slot's prefix, so the fleet sample total
        is conserved (the neighbor re-visits those few samples — the
        documented with-replacement perturbation)."""
        mgr = _FakeSlot(frac=1.25)
        s = ElasticSampler(64, mgr, batch_size=8, seed=5)
        idx = s.next_indices()
        assert len(idx) == 10
        perm = s._perm(0)
        np.testing.assert_array_equal(idx, perm[:10])
        neighbor = s.indices_for_slot(1)
        np.testing.assert_array_equal(idx[8:], neighbor[:2])
        assert mgr.reported == [10]

    def test_draw_truncates_at_epoch_edge(self):
        s = ElasticSampler(64, _FakeSlot(), batch_size=8)
        # Last slot of the epoch: the boost has nowhere to overflow.
        assert len(s.indices_for_slot(7, 1.25)) == 8
        assert len(s.indices_for_slot(7, 0.5)) == 4

    def test_reports_samples_truth_table(self):
        class NoReport:
            pass

        class NoProbe:
            set_step_samples = staticmethod(lambda n: None)

        assert not _reports_samples(NoReport(), 0.5)
        assert _reports_samples(NoProbe(), 1.0)  # test doubles: always
        mgr = _FakeSlot(degraded=False)
        assert _reports_samples(mgr, 0.75)
        assert _reports_samples(mgr, 1.1667)  # boost reports too
        assert not _reports_samples(mgr, 1.0)
        mgr_deg = _FakeSlot(degraded=True)
        assert _reports_samples(mgr_deg, 1.0)


# --------------------------------------------------- chaos `slow:` band


class TestChaosSlowBand:
    def teardown_method(self):
        chaos.reset()

    def test_spec_parses_slow_fields(self):
        sched = chaos.parse_spec(
            "seed=7;slow:slow_rate=1.0,slow_factor=3.0")
        cfg = sched.config_for("slow:anygroup")
        assert cfg.slow_rate == 1.0 and cfg.slow_factor == 3.0

    def test_no_config_no_decision_draw_stream_purity(self):
        """Like the sdc band: with no `slow` channel configured the
        hook returns 1.0 WITHOUT drawing a decision, so existing
        channels' traces are byte-identical whether or not the caller
        polls the slow band."""
        sched = chaos.parse_spec("seed=1;serve:reset_rate=0.5")
        assert chaos.slow_fault("slow:g0", sched) == 1.0
        assert "slow" not in sched._counts
        assert chaos.slow_fault("slow:g0") == 1.0  # nothing installed

    def test_persistent_straggler_every_boundary(self):
        sched = chaos.parse_spec(
            "seed=2;slow:slow_rate=1.0,slow_factor=2.5")
        got = [chaos.slow_fault("slow:g0", sched) for _ in range(8)]
        assert got == [2.5] * 8

    def test_deterministic_per_seed(self):
        mk = lambda: chaos.parse_spec(  # noqa: E731
            "seed=9;slow:slow_rate=0.5,slow_factor=2.0")
        a, b = mk(), mk()
        seq_a = [chaos.slow_fault("slow:g0", a) for _ in range(40)]
        seq_b = [chaos.slow_fault("slow:g0", b) for _ in range(40)]
        assert seq_a == seq_b
        assert set(seq_a) == {1.0, 2.0}

    def test_intensity_scales_rate_not_factor(self):
        """The PhasedChaos knob: intensity 0 mints no stretch (the
        stable phases), intensity 1 restores the configured rate —
        while slow_factor is a multiplier and never scales."""
        sched = chaos.parse_spec(
            "seed=3;slow:slow_rate=1.0,slow_factor=2.0")
        sched.set_intensity(0.0)
        assert all(chaos.slow_fault("slow:g0", sched) == 1.0
                   for _ in range(10))
        sched.set_intensity(1.0)
        assert chaos.slow_fault("slow:g0", sched) == 2.0

    def test_factor_below_one_clamps_to_no_stretch(self):
        sched = chaos.parse_spec(
            "seed=4;slow:slow_rate=1.0,slow_factor=0.25")
        assert chaos.slow_fault("slow:g0", sched) == 1.0

    def test_manager_hook_stretches_natural_wall(self):
        """step()'s injection point: a participant under a slow_rate=1
        schedule sleeps (factor-1) x the natural boundary wall — and
        subtracts its OWN prior injection from the measured wall, so
        the stretch converges instead of compounding (at factor >= 2
        the naive spelling diverges)."""
        chaos.install(chaos.parse_spec(
            "seed=5;slow:slow_rate=1.0,slow_factor=3.0"))
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = True
        m = make_manager(client, replica_id="slowmgr")
        try:
            boundary(m)  # establishes participation + the prev stamp
            time.sleep(0.05)
            t0 = time.monotonic()
            m._maybe_chaos_slow()
            slept = time.monotonic() - t0
            first = m._chaos_slow_injected
            assert first >= 0.05  # ~2x the ~0.05 s natural wall
            assert slept >= first * 0.9
            # Immediately again: the wall is almost all injected sleep,
            # so the natural remainder — and the new injection — is
            # tiny (convergence, not compounding).
            m._maybe_chaos_slow()
            assert m._chaos_slow_injected < first * 0.5
        finally:
            m.shutdown()
            chaos.reset()

    def test_manager_hook_participants_only_no_draw(self):
        """A healer/spare contributes no wall the Rebalancer reads, so
        it must not sleep — and must not draw either (stream purity
        for the shared channel)."""
        sched = chaos.parse_spec(
            "seed=6;slow:slow_rate=1.0,slow_factor=4.0")
        chaos.install(sched)
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = True
        m = make_manager(client, replica_id="benched")
        try:
            boundary(m)
            with m._metrics_lock:
                m._healing = False
            m._participating_rank = None  # benched spare
            draws_before = sched._counts.get("slow", 0)
            time.sleep(0.02)
            m._maybe_chaos_slow()
            assert m._chaos_slow_injected == 0.0
            assert sched._counts.get("slow", 0) == draws_before
        finally:
            m.shutdown()
            chaos.reset()


# ------------------------------------- composed-fraction weighted fold


class TestComposedFractionFold:
    """The acceptance gate's numeric half: the wire-v4 weighted fold
    at rebalance-composed weights is BITWISE identical on every rank
    to the single-process oracle — zero new wire format, the same
    ring, just the draws as weights."""

    @pytest.mark.parametrize("world,fracs", [
        (2, [1.1667, 0.375]),        # boost x (degrade 0.5 x reb 0.75)
        (3, [1.1667, 0.5, 1.0]),     # floor straggler + boost + plain
        (4, [1.1667, 1.1667, 0.5, 1.1667]),  # the parity-trace fleet
    ])
    def test_bitwise_matches_oracle_at_composed_weights(self, world,
                                                        fracs):
        batch = 48
        weights = [int(round(batch * f)) for f in fracs]
        rng = np.random.default_rng(world)
        xs = [rng.normal(size=10_007).astype(np.float32)
              for _ in range(world)]
        out, errors = _run_ring(
            world, lambda c, ring, r: c._do_allreduce_wire(
                ring, [xs[r].copy()], [np.dtype(np.float32)], "sum",
                "step", weights[r]))
        assert not errors, errors
        expected = weighted_oracle(xs, weights)
        for o in out:
            np.testing.assert_array_equal(o[0], expected)


# ------------------------------------------------- aggregator coupling


class TestFleetAggregatorRebalance:
    def _drive(self, agg, walls, step):
        for rid in sorted(walls):
            agg.ingest(fleet.StepDigest(replica_id=rid, step=step,
                                        step_wall_ms=walls[rid]))
        return agg.aggregate()

    def test_aggregate_drives_ladder_and_exposes_table(self):
        agg = fleet.FleetAggregator()
        walls = {"a": 100.0, "b": 100.0, "c": 400.0, "d": 100.0}
        for k in range(1, 4):
            out = self._drive(agg, walls, step=k)
        fl = out["fleet"]
        assert fl["rebalance_fractions"]["c"] == 0.875
        assert fl["rebalance_seq"] == 1
        assert fl["rebalance_shrinks_total"] == 1
        assert "c=0.8750" in fl["rebalance_table"]
        by_id = {g["replica_id"]: g for g in out["groups"]}
        assert by_id["c"]["rebalance_fraction"] == 0.875
        assert by_id["a"]["rebalance_fraction"] > 1.0

    def test_healing_digest_ineligible_for_ladder(self):
        agg = fleet.FleetAggregator()
        for k in range(1, 6):
            for rid, wall in (("a", 100.0), ("b", 100.0),
                              ("d", 100.0)):
                agg.ingest(fleet.StepDigest(replica_id=rid, step=k,
                                            step_wall_ms=wall))
            agg.ingest(fleet.StepDigest(replica_id="c", step=k,
                                        step_wall_ms=400.0,
                                        healing=True))
            out = agg.aggregate()
        assert out["fleet"]["rebalance_shrinks_total"] == 0
        assert out["fleet"]["rebalance_table"] == ""

    def test_remove_forgets_fraction_immediately(self):
        """The farewell path: a departed group's slice is gone the same
        round — no ghost deficit keeps boosting the survivors."""
        agg = fleet.FleetAggregator()
        walls = {"a": 100.0, "b": 100.0, "c": 400.0, "d": 100.0}
        for k in range(1, 4):
            self._drive(agg, walls, step=k)
        assert agg.rebalancer.shrinks_total == 1
        agg.remove("c")
        out = self._drive(agg, {"a": 100.0, "b": 100.0, "d": 100.0},
                          step=4)
        assert out["fleet"]["rebalance_fractions"] == {}
        assert out["fleet"]["rebalance_table"] == ""

    def test_reported_fraction_normalizes_the_wall(self):
        """The anti-flap half: once shrunk, the digest reports its
        fraction and the ladder judges wall/fraction — a straggler
        whose RAW wall normalized back to the fleet's stays shrunk
        (no shrink -> restore -> shrink oscillation)."""
        agg = fleet.FleetAggregator()
        walls = {"a": 100.0, "b": 100.0, "c": 400.0, "d": 100.0}
        for k in range(1, 4):
            self._drive(agg, walls, step=k)
        assert agg.rebalancer.fractions()["c"] == 0.875
        seq_after_shrink = agg.rebalancer.seq
        # c now reports 0.875 and its raw wall shrank proportionally:
        # normalized it is still 400 — loud, not quiet. 6+ boundaries
        # at the would-be-restore cadence must NOT restore it.
        for k in range(4, 12):
            for rid in ("a", "b", "d"):
                agg.ingest(fleet.StepDigest(replica_id=rid, step=k,
                                            step_wall_ms=100.0))
            agg.ingest(fleet.StepDigest(
                replica_id="c", step=k, step_wall_ms=400.0 * 0.875,
                rebalance_fraction=0.875))
            agg.aggregate()
        assert agg.rebalancer.restores_total == 0
        assert agg.rebalancer.fractions()["c"] < 0.875  # kept sinking
        assert agg.rebalancer.seq > seq_after_shrink


# ----------------------------------------------- nightly shrink/restore


@pytest.mark.slow
@pytest.mark.nightly
class TestRebalanceSoak:
    """The seeded stable -> storm -> stable acceptance soak, pure
    Python end-to-end: the chaos ``slow:`` band mints a persistent 4x
    straggler for the storm phase (intensity is the PhasedChaos knob,
    driven here by boundary count so the soak is deterministic), the
    real FleetAggregator + Rebalancer walk the ladder down to the
    floor and symmetrically back, with ZERO table changes inside the
    settled stable windows — and the final fold at the storm-peak
    fractions is bitwise against the oracle."""

    def test_storm_shrinks_stable_restores_zero_flap(self):
        sched = chaos.parse_spec(
            "seed=11;slow:slow_rate=1.0,slow_factor=4.0")
        agg = fleet.FleetAggregator()
        base = {"a": 100.0, "b": 100.0, "c": 100.0, "d": 100.0}
        assigned = {rid: 1.0 for rid in base}
        seq_at = {}
        frac_c = {}
        for k in range(1, 121):
            # stable(20) -> storm(40) -> stable(60), by boundary count.
            sched.set_intensity(1.0 if 21 <= k <= 60 else 0.0)
            factor = chaos.slow_fault("slow:c", sched)
            reported = dict(assigned)  # adopted at the last boundary
            for rid in sorted(base):
                stretch = factor if rid == "c" else 1.0
                agg.ingest(fleet.StepDigest(
                    replica_id=rid, step=k,
                    step_wall_ms=base[rid] * reported[rid] * stretch,
                    rebalance_fraction=reported[rid]))
            out = agg.aggregate()
            assigned = {rid: out_g["rebalance_fraction"]
                        for out_g in out["groups"]
                        for rid in [out_g["replica_id"]]}
            seq_at[k] = agg.rebalancer.seq
            frac_c[k] = agg.rebalancer.fractions()["c"]

        # Initial stable phase: a uniform fleet, untouched table.
        assert seq_at[20] == 0 and frac_c[20] == 1.0
        # Storm: c walked to the floor and LATCHED there — no flap in
        # the storm's settled tail.
        assert frac_c[60] == fleet.REBALANCE_FLOOR
        assert seq_at[60] == seq_at[45], "table flapped at the floor"
        assert agg.rebalancer.shrinks_total == 4
        # Final stable phase: symmetric restore, then a settled window
        # with zero table changes, ending uniform.
        assert frac_c[120] == 1.0
        assert agg.rebalancer.restores_total == 4
        assert agg.rebalancer.table == ""
        assert seq_at[120] == seq_at[105], "table flapped after restore"
        # 4 shrinks down + 4 restores up, each a table change, plus the
        # final change back to the empty table: the whole 120-boundary
        # soak moved the fleet exactly 8 times.
        assert seq_at[120] == 8

        # Bitwise fold at the storm-peak fractions (floor + boosts).
        batch = 64
        fracs = [1.1667, 1.1667, 0.5, 1.1667]
        weights = [int(round(batch * f)) for f in fracs]
        rng = np.random.default_rng(11)
        xs = [rng.normal(size=4_099).astype(np.float32)
              for _ in range(4)]
        out, errors = _run_ring(
            4, lambda c, ring, r: c._do_allreduce_wire(
                ring, [xs[r].copy()], [np.dtype(np.float32)], "sum",
                "step", weights[r]))
        assert not errors, errors
        expected = weighted_oracle(xs, weights)
        for o in out:
            np.testing.assert_array_equal(o[0], expected)
