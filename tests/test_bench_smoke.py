"""Bench-smoke tier: the bench's allreduce A/B scenarios at tiny sizes
under ``JAX_PLATFORMS=cpu`` (conftest forces it), as a fast regression
gate for the pipelined host allreduce — run via ``scripts/test.sh
bench-smoke``. Includes a chaos-enabled variant driving ``TORCHFT_CHAOS``
short-read faults through the wire-dtype segment-upcast path (the ring
recovers via the poison/recovery rendezvous from the chaos PR and the
run still completes).

Marked ``bench_smoke`` + ``slow`` so the tier-1 per-commit suite's wall
clock is unaffected.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402
import pytest  # noqa: E402

import conftest  # noqa: E402

requires_native = conftest.requires_native()

pytestmark = [pytest.mark.bench_smoke, pytest.mark.slow]


@requires_native
class TestAllreduceABSmoke:
    def _mg(self, **kw):
        from bench import bench_multigroup

        base = dict(n_groups=2, steps=2, hidden=48)
        base.update(kw)
        return bench_multigroup(**base)

    def test_single_shot_vs_bucketed(self):
        single = self._mg(bucket_bytes=1 << 40)
        bucketed = self._mg(bucket_bytes=4096)
        for out in (single, bucketed):
            assert out["steps_per_s"] > 0
            stages = out["stages_ms"]
            assert stages["ring"] > 0
            assert stages["fetch_dispatch"] >= 0
            assert stages["fetch_wait"] >= 0
            assert np.isfinite(out["allreduce_ms_avg"])
        # Same gradient, same exact numerics: both move the same bytes
        # per step on both legs regardless of bucketing.
        assert bucketed["wire_mbytes_per_step"] == pytest.approx(
            single["wire_mbytes_per_step"], rel=0.01)
        assert bucketed["ring_wire_mbytes_per_step"] == pytest.approx(
            single["ring_wire_mbytes_per_step"], rel=0.01)

    def test_bf16_wire_halves_both_legs(self):
        import jax.numpy as jnp

        exact = self._mg(bucket_bytes=4096)
        wire = self._mg(bucket_bytes=4096, wire_dtype=jnp.bfloat16)
        assert wire["steps_per_s"] > 0
        # The MLP gradient is all-f32, so bf16 wire must halve BOTH the
        # D2H leg and — now that the narrow dtype rides the ring
        # end-to-end — the TCP leg.
        assert wire["wire_mbytes_per_step"] == pytest.approx(
            exact["wire_mbytes_per_step"] / 2, rel=0.02)
        assert wire["ring_wire_mbytes_per_step"] == pytest.approx(
            exact["ring_wire_mbytes_per_step"] / 2, rel=0.02)

    def test_chaos_short_read_on_wire_ring(self):
        """A seeded short-read fault injected into the ring's data plane
        lands mid-collective in the wire path's segment upcast loop; the
        step aborts cleanly, the poisoned ring rebuilds on the recovery
        rendezvous, and the run still commits every requested step."""
        import jax.numpy as jnp

        from torchft_tpu import chaos

        chaos.install(chaos.parse_spec(
            "seed=7;ring:short_rate=0.05,max_faults=1"))
        try:
            out = self._mg(steps=3, bucket_bytes=4096,
                           wire_dtype=jnp.bfloat16)
            assert out["steps_per_s"] > 0
            assert out["ring_wire_mbytes_per_step"] > 0
        finally:
            chaos.uninstall()
