"""Bench-smoke tier: the bench's allreduce A/B scenarios at tiny sizes
under ``JAX_PLATFORMS=cpu`` (conftest forces it), as a fast regression
gate for the pipelined host allreduce — run via ``scripts/test.sh
bench-smoke``. Includes a chaos-enabled variant driving ``TORCHFT_CHAOS``
short-read faults through the wire-dtype segment-upcast path (the ring
recovers via the poison/recovery rendezvous from the chaos PR and the
run still completes).

Marked ``bench_smoke`` + ``slow`` so the tier-1 per-commit suite's wall
clock is unaffected.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402
import pytest  # noqa: E402

import conftest  # noqa: E402

requires_native = conftest.requires_native()

pytestmark = [pytest.mark.bench_smoke, pytest.mark.slow]


@requires_native
class TestAllreduceABSmoke:
    def _mg(self, **kw):
        from bench import bench_multigroup

        base = dict(n_groups=2, steps=2, hidden=48)
        base.update(kw)
        return bench_multigroup(**base)

    def test_single_shot_vs_bucketed(self):
        single = self._mg(bucket_bytes=1 << 40)
        bucketed = self._mg(bucket_bytes=4096)
        for out in (single, bucketed):
            assert out["steps_per_s"] > 0
            stages = out["stages_ms"]
            assert stages["ring"] > 0
            assert stages["fetch_dispatch"] >= 0
            assert stages["fetch_wait"] >= 0
            assert np.isfinite(out["allreduce_ms_avg"])
        # Same gradient, same exact numerics: both move the same bytes
        # per step on both legs regardless of bucketing.
        assert bucketed["wire_mbytes_per_step"] == pytest.approx(
            single["wire_mbytes_per_step"], rel=0.01)
        assert bucketed["ring_wire_mbytes_per_step"] == pytest.approx(
            single["ring_wire_mbytes_per_step"], rel=0.01)

    def test_bf16_wire_halves_both_legs(self):
        import jax.numpy as jnp

        exact = self._mg(bucket_bytes=4096)
        wire = self._mg(bucket_bytes=4096, wire_dtype=jnp.bfloat16)
        assert wire["steps_per_s"] > 0
        # The MLP gradient is all-f32, so bf16 wire must halve BOTH the
        # D2H leg and — now that the narrow dtype rides the ring
        # end-to-end — the TCP leg.
        assert wire["wire_mbytes_per_step"] == pytest.approx(
            exact["wire_mbytes_per_step"] / 2, rel=0.02)
        assert wire["ring_wire_mbytes_per_step"] == pytest.approx(
            exact["ring_wire_mbytes_per_step"] / 2, rel=0.02)

    def test_bf16_wire_fetch_within_1p2x_of_exact_at_8mb(self):
        """The BENCH_r05 regression gate: at the ~8.6MB payload, bf16
        wire mode's fetch stage must stay within 1.2x of the exact
        path's — it moves HALF the bytes, so anything past that bound
        means the fetch fell off the packed fast path again (per-step
        retrace, or a non-canonical-dtype transfer slow path; the pack
        now ships canonical uint bits precisely to keep off the
        latter). Cache-miss counters must also freeze after warmup."""
        import jax.numpy as jnp

        big = dict(hidden=1024, depth=3, steps=3,
                   bucket_bytes=2 << 20)
        exact = self._mg(**big)
        wire = self._mg(wire_dtype=jnp.bfloat16, **big)
        # Byte sanity: the halving actually happened on both legs.
        assert wire["wire_mbytes_per_step"] == pytest.approx(
            exact["wire_mbytes_per_step"] / 2, rel=0.02)
        # The acceptance bound, with a small absolute cushion for
        # timer noise on near-zero stage times.
        assert wire["stages_ms"]["fetch"] <= \
            exact["stages_ms"]["fetch"] * 1.2 + 2.0, (exact, wire)

    def test_overlap_ab_smoke(self):
        """Sync vs cross-step-overlap A/B plumbing at tiny size: the
        overlap run completes, reports its hidden/drain attribution,
        and drops nothing on the happy path. (The >=1.5x performance
        assertion lives in tests/test_overlap.py with a deterministic
        slowed ring; at smoke sizes the exchange is too fast for a
        meaningful ratio.)"""
        sync = self._mg(steps=3)
        ov = self._mg(steps=3, overlap_steps=1)
        assert sync["overlap_steps"] == 0
        assert ov["overlap_steps"] == 1
        assert ov["steps_per_s"] > 0
        assert ov["hidden_ms_avg"] >= 0.0
        assert ov["drain_wait_ms_avg"] >= 0.0
        assert sync["hidden_ms_avg"] == 0.0  # sync mode never defers

    def test_trace_ab_smoke(self):
        """Tracing on/off A/B plumbing at tiny size: both runs
        complete and the tracing=False leg really records nothing (the
        <2% overhead assertion is the bench's multigroup_8mb_trace_ab
        row — smoke sizes are dispatch-bound noise)."""
        on = self._mg(steps=3, tracing=True)
        off = self._mg(steps=3, tracing=False)
        assert on["steps_per_s"] > 0
        assert off["steps_per_s"] > 0

    def test_devquant_ab_smoke(self):
        """Device-vs-host wire-quantization A/B plumbing at tiny size
        (docs/design/hier_transport.md): the int8-policy device leg
        fetches the wire payload (~1/4 of the host leg's f32 D2H) and
        both legs report the fetch accounting the
        multigroup_8mb_devquant_ab row is built from. (The 0.6x
        fetch-ms gate is the bench row's — smoke sizes are
        dispatch-bound noise; the BITWISE identity of the two legs is
        frozen native-free in tests/test_transport.py.)"""
        from torchft_tpu import policy as policy_mod

        int8 = next(p for p in policy_mod.LADDER
                    if p.name == "sync-int8")
        dev = self._mg(steps=2, policy=int8, device_quantize=True)
        host = self._mg(steps=2, policy=int8, device_quantize=False)
        assert dev["steps_per_s"] > 0 and host["steps_per_s"] > 0
        assert 0 < dev["fetch_mbytes_per_step"] \
            < 0.3 * host["fetch_mbytes_per_step"], (dev, host)
        assert dev["ring_topology"] == "flat"

    def test_hier_ab_smoke(self):
        """Flat vs hierarchical transport A/B plumbing at tiny size: 4
        groups as 2 simulated hosts x 2 build the two-level ring
        (topology string reports it), results stay byte-accounted per
        leg, and the cross-host (leader) bytes land under the flat
        ring's total — the scaling gate the multigroup_8mb_hier_ab row
        asserts at 8MB. (Bitwise flat-vs-hier identity is frozen
        native-free in tests/test_transport.py.)"""
        flat = self._mg(n_groups=4, steps=2)
        hier = self._mg(n_groups=4, steps=2, hier_hosts=2)
        assert flat["ring_topology"] == "flat"
        assert hier["ring_topology"] == "hier:2x2"
        assert hier["steps_per_s"] > 0
        assert hier["hier_intra_mbytes_per_step"] > 0
        assert hier["hier_leader_mbytes_per_step"] > 0
        assert hier["hier_leader_mbytes_per_step"] <= \
            flat["ring_wire_mbytes_per_step_total"] / 2, (flat, hier)

    def test_chaos_short_read_on_wire_ring(self):
        """A seeded short-read fault injected into the ring's data plane
        lands mid-collective in the wire path's segment upcast loop; the
        step aborts cleanly, the poisoned ring rebuilds on the recovery
        rendezvous, and the run still commits every requested step."""
        import jax.numpy as jnp

        from torchft_tpu import chaos

        chaos.install(chaos.parse_spec(
            "seed=7;ring:short_rate=0.05,max_faults=1"))
        try:
            out = self._mg(steps=3, bucket_bytes=4096,
                           wire_dtype=jnp.bfloat16)
            assert out["steps_per_s"] > 0
            assert out["ring_wire_mbytes_per_step"] > 0
        finally:
            chaos.uninstall()


class TestPublishFanoutSmoke:
    """Publish-fanout bench plumbing at tiny size — pure-python
    transport, no native library needed. The full-scale >=4x capacity
    gate runs in bench.py (relays=6, 4MB payload); at smoke scale we
    assert the machinery: both legs complete, the direct leg respects
    the uplink cap, the relay tier beats direct, and the small-touch
    delta ratio is ~changed/total."""

    def test_publish_fanout_plumbing(self):
        from bench import bench_publish_fanout

        out = bench_publish_fanout(
            payload_mb=0.6, subscribers=4, relays=3, uplink_mb_s=24.0,
            publishes=2, capacity_secs=1.5)
        assert out["publish_to_visible_p50_ms"] > 0
        assert out["publish_to_visible_p95_ms"] >= \
            out["publish_to_visible_p50_ms"]
        # small-touch publish moved ~1/12 of the payload
        assert out["delta_full_ratio"] == pytest.approx(1 / 12, rel=0.05)
        # direct leg is uplink-bound: within the cap (+ scheduling slop)
        assert out["direct_agg_mb_s"] <= 24.0 * 1.15
        assert out["direct_syncs"] >= 1
        # the relay tier multiplies capacity (full 4x gate at bench
        # scale where per-sync overhead amortizes; at smoke scale the
        # measured ratio is ~1.7, and >=1.3x is already impossible
        # without a working tier — direct is pinned at one uplink)
        assert out["fanout_capacity_ratio"] >= 1.3, out

    def test_emitted_rows_carry_provenance(self, capsys):
        import bench

        bench._emit({"metric": "smoke"})
        err = capsys.readouterr().err.strip().splitlines()[-1]
        import json as _json

        row = _json.loads(err)
        assert row["metric"] == "smoke"
        assert row["schema"] == bench._BENCH_SCHEMA
        assert row["platform"] == "cpu"
        assert "jax" in row and row["jax"]
        # Observability provenance (docs/design/observability.md):
        # whether tracing was on while the row was measured, and where
        # the flight recorder would dump ("" = off).
        assert isinstance(row["tracing_enabled"], bool)
        assert "flight_dir" in row
        # host shape: benchdiff skips throughput comparisons across
        # machine-shape changes, so every row must carry its cpu count
        assert row["host_cpus"] >= 1


class TestBenchdiffSmoke:
    """Native-free smoke of scripts/benchdiff.py — the bench
    trajectory's regression gate (docs/design/fleet_health.md). The
    deeper unit battery (direction vocabulary, wrapper parsing,
    trajectory gating) is tier-1 in tests/test_fleet.py."""

    def _write(self, path, rows):
        import json as _json

        path.write_text(
            "\n".join(_json.dumps(r) for r in rows) + "\n")

    def test_regression_exits_nonzero(self, tmp_path):
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                              / "scripts"))
        try:
            import benchdiff
        finally:
            sys.path.pop(0)
        old = tmp_path / "BENCH_r01.json"
        new = tmp_path / "BENCH_r02.json"
        self._write(old, [{"metric": "multigroup_steps_per_s",
                           "value": 1.0, "unit": "steps/s",
                           "stages_ms": {"ring": 100.0}}])
        self._write(new, [{"metric": "multigroup_steps_per_s",
                           "value": 0.5, "unit": "steps/s",
                           "stages_ms": {"ring": 240.0}}])
        assert benchdiff.main([str(old), str(new)]) == 1
        # within threshold -> clean exit
        self._write(new, [{"metric": "multigroup_steps_per_s",
                           "value": 0.97, "unit": "steps/s",
                           "stages_ms": {"ring": 104.0}}])
        assert benchdiff.main([str(old), str(new)]) == 0

    def test_real_trajectory_parses(self):
        """The repo's own BENCH_r*.json trajectory must stay parseable
        (the driver-wrapper spelling) — rows keyed by metric with
        numeric fields."""
        sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                              / "scripts"))
        try:
            import benchdiff
        finally:
            sys.path.pop(0)
        repo = Path(__file__).resolve().parent.parent
        files = benchdiff.trajectory_files(str(repo))
        if len(files) < 2:
            pytest.skip("no bench trajectory in the working tree")
        rows = benchdiff.parse_bench_file(files[-1])
        assert rows, "newest bench file yielded no rows"
        assert all("metric" in r for r in rows.values())
