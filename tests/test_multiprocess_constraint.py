"""Pins the platform constraint that rules out a process-spanning device
path (docs/design/cross_group_backend.md): in a multi-process
``jax.distributed`` runtime, the coordination service hard-kills SURVIVING
processes when any task dies — even while they execute purely local
computations. A cross-group backend built on one shared runtime would
therefore die with the first group failure, the exact event this framework
exists to survive.

If this test ever FAILS (the survivor outlives the peer's death), the
platform has grown fail-soft semantics and tier 3 of the backend design
becomes buildable — revisit the design doc.
"""

import socket
import subprocess
import sys
import textwrap

import pytest

WORKER = textwrap.dedent("""
    import os, sys, time
    pid = int(sys.argv[1]); coord = sys.argv[2]
    import jax
    from jax.extend.backend import clear_backends
    clear_backends()
    jax.config.update("jax_num_cpu_devices", 2)
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=coord, num_processes=2,
                               process_id=pid,
                               heartbeat_timeout_seconds=10)
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices()).reshape(2, 2), ("group", "intra"))
    local = np.full((1, 4), float(pid + 1), np.float32)
    sharding = NamedSharding(mesh, P("group", None))
    garr = jax.make_array_from_process_local_data(sharding, local, (2, 4))
    out = jax.jit(lambda x: jnp.sum(x, axis=0),
                  out_shardings=NamedSharding(mesh, P()))(garr)
    assert float(np.asarray(out.addressable_shards[0].data)[0]) == 3.0
    print(f"[{pid}] allreduce ok", flush=True)
    if pid == 1:
        os._exit(1)  # the "replica group death"
    f = jax.jit(lambda x: (x * 2).sum())
    for i in range(20):  # purely LOCAL work; no cross-process collectives
        time.sleep(2)
        print(f"[0] local ok {float(f(jnp.arange(8.0)))}", flush=True)
    print("[0] SURVIVED", flush=True)
""")


@pytest.mark.integration
def test_peer_death_kills_survivor_in_shared_jax_runtime(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    procs = [
        subprocess.Popen([sys.executable, str(script), str(pid), coord],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True)
        for pid in (0, 1)
    ]
    out0, _ = procs[0].communicate(timeout=120)
    procs[1].wait(timeout=30)
    assert "[0] allreduce ok" in out0          # the shared path does work...
    assert "local ok" in out0                  # ...and local work continues...
    assert "[0] SURVIVED" not in out0          # ...until the service kills us
    assert procs[0].returncode != 0, (
        "survivor outlived peer death — the platform constraint has "
        "lifted; revisit docs/design/cross_group_backend.md tier 3")
