"""State-attestation tests (docs/design/state_attestation.md).

Tier-1 and native-free (marker ``sdc``, ``scripts/test.sh sdc``): the
device digest kernel frozen against the NumPy reference
(:func:`torchft_tpu.serialization.attest_fingerprint`) across dtypes
plus its trace-time cache tripwire, the pure-Python
:class:`~torchft_tpu.fleet.FleetAggregator` vote (strict majority,
healer/absent/foreign-quorum abstention, sticky latch, the non-voter
clear-on-match, farewell-vs-prune clearing), the satellite-1
read-time staleness bound (a SIGKILLed group ages out of baselines
AND ballots), the ONE shared donor-admission predicate across all
three resolvers (in-quorum healset, pre-join, RAM replication
targets), the Manager quarantine ladder (latch, refusal classes,
serve-gate 503, withdrawn advertisements, verdict-clear rules), the
chaos ``sdc`` band (spec parse, stream purity, intensity/PhasedChaos
composition, determinism, the participants-only injection contract),
and the seeded 3-group soak: one bit flip -> verdict within one
commit boundary -> auto-heal from the attested majority -> bitwise
fleet convergence and a clean latch.

The C++ lighthouse runs the same vote (lighthouse.cc — the mirror
contract); its unit matrix lives in ``_core/core_test.cc`` and the
native parity round rides nightly.
"""

import threading
import urllib.error
import urllib.request
from unittest.mock import MagicMock

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchft_tpu import chaos, fleet, serialization
from torchft_tpu._native import QuorumResult
from torchft_tpu.chaos import ChaosSchedule, EndpointChaos
from torchft_tpu.checkpointing import CheckpointServer
from torchft_tpu.communicator import DummyCommunicator
from torchft_tpu.fleet import FleetAggregator, StepDigest
from torchft_tpu.manager import (_PACK_STATS, Manager, _addr_base,
                                 _attest_device_words)
from torchft_tpu.policy import PhasedChaos

pytestmark = pytest.mark.sdc

NOW = 1_000_000  # fixed aggregator clock base (ms)


def mk_digest(rid, step=5, wall=100.0, healing=False, capacity=1.0,
              quorum_id=1, state_digest="", trace_addr=""):
    return StepDigest(replica_id=rid, step=step, step_wall_ms=wall,
                      healing=healing, capacity_fraction=capacity,
                      quorum_id=quorum_id, state_digest=state_digest,
                      trace_addr=trace_addr)


def quorum_result(quorum_id=1, recover_manager_address="m:1",
                  store_address="s:1", max_step=1, max_rank=0,
                  max_world_size=3, replica_rank=0,
                  replica_world_size=3, heal=False, **kw):
    return QuorumResult(
        quorum_id=quorum_id,
        recover_manager_address=recover_manager_address,
        store_address=store_address, max_step=max_step,
        max_rank=max_rank, max_world_size=max_world_size,
        replica_rank=replica_rank,
        replica_world_size=replica_world_size, heal=heal, **kw)


def make_manager(client=None, replica_id="sdc0", **kw):
    if client is None:
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = True
    return Manager(
        comm=DummyCommunicator(),
        load_state_dict=kw.pop("load_state_dict", MagicMock()),
        state_dict=kw.pop("state_dict",
                          lambda: {"w": np.arange(8, dtype=np.float32)}),
        min_replica_size=1,
        use_async_quorum=False,
        rank=0, world_size=1,
        replica_id=replica_id,
        _manager_client=client,
        **kw,
    )


class FakeStore:
    """Dict-backed stand-in for the native StoreClient (same shape the
    churn tests inject via ``Manager._healset_store``)."""

    def __init__(self):
        self.kv = {}
        self.lock = threading.Lock()

    def set(self, key, value):
        with self.lock:
            self.kv[key] = value if isinstance(value, bytes) \
                else str(value).encode()

    def get(self, key, timeout_ms=0):
        with self.lock:
            if key not in self.kv:
                raise KeyError(key)
            return self.kv[key]


# ------------------------------------------------------- digest kernel


class TestDigestKernel:
    """The jitted device fingerprint is FROZEN against the NumPy
    reference: u32 wraparound arithmetic is associative, so the
    device's per-add wrap and the reference's u64-sum-then-mask must
    agree bit-for-bit on the same bytes."""

    CASES = [
        np.arange(37, dtype=np.float32) * 0.7,
        np.arange(-8, 8, dtype=np.int32),
        np.arange(256, dtype=np.uint8),
        np.array([True, False, True, True]),
    ]

    def _device_digest(self, arrays):
        leaves = [jax.device_put(a) for a in arrays]
        words = np.asarray(_attest_device_words(leaves), dtype=np.uint32)
        return serialization.attest_combine([int(w) for w in words])

    def test_device_matches_numpy_reference(self):
        for a in self.CASES:
            dev = jax.device_put(a)
            # Hash the SAME bytes the device holds (device_put may
            # legitimately canonicalize dtypes, e.g. f64 -> f32).
            ref = serialization.attest_fingerprint([np.asarray(dev)])
            assert self._device_digest([a]) == ref, a.dtype

    def test_bfloat16_leaf(self):
        dev = jnp.arange(33, dtype=jnp.bfloat16) * jnp.bfloat16(0.5)
        words = np.asarray(_attest_device_words([dev]), dtype=np.uint32)
        got = serialization.attest_combine([int(w) for w in words])
        assert got == serialization.attest_fingerprint([np.asarray(dev)])

    def test_multi_leaf_fold_and_order_sensitivity(self):
        a = np.arange(16, dtype=np.float32)
        b = np.arange(9, dtype=np.int32)
        assert self._device_digest([a, b]) == \
            serialization.attest_fingerprint([a, b])
        # Pytree order is part of the fingerprint: swapped leaves must
        # NOT collide (the fold is non-commutative by construction).
        assert self._device_digest([a, b]) != self._device_digest([b, a])

    def test_single_bit_flip_changes_digest(self):
        a = np.arange(64, dtype=np.float32)
        clean = self._device_digest([a])
        for byte, bit in ((0, 0), (17, 3), (255, 7)):
            c = a.copy()
            c.view(np.uint8)[byte] ^= np.uint8(1 << bit)
            assert self._device_digest([c]) != clean, (byte, bit)

    def test_digest_is_32_hex_chars(self):
        d = self._device_digest([np.ones(4, np.float32)])
        assert len(d) == 32
        int(d, 16)  # must parse as hex

    def test_trace_time_cache_tripwire(self):
        """The kernel caches per leaf-signature jit functions; a
        recompile storm (shape-unstable state trees) must show up in
        the sdc_digest_cache_misses counter, which counts COMPILES
        (trace-time bumps), not calls."""
        leaves = [jax.device_put(np.arange(11, dtype=np.float32))]
        _attest_device_words(leaves)  # warm (may or may not compile)
        before = _PACK_STATS["sdc_digest_cache_misses"]
        for _ in range(5):
            _attest_device_words(leaves)  # cached: no new trace
        assert _PACK_STATS["sdc_digest_cache_misses"] == before
        fresh = [jax.device_put(np.arange(13, dtype=np.float32))]
        _attest_device_words(fresh)  # new signature: exactly one trace
        assert _PACK_STATS["sdc_digest_cache_misses"] == before + 1

    def test_manager_digest_host_fallback_matches_reference(self):
        m = make_manager(
            state_dict=lambda: {"w": np.arange(8, dtype=np.float32),
                                "meta": "not-an-array"})
        try:
            got = m._compute_state_digest()
            assert got == serialization.attest_fingerprint(
                [np.arange(8, dtype=np.float32)])
            assert m.metrics()["sdc_digests_total"] == 1.0
            assert m._last_state_digest == got
        finally:
            m.shutdown()

    def test_manager_digest_device_path_matches_host_path(self):
        arr = np.arange(24, dtype=np.float32) * 3.0
        dev = make_manager(state_dict=lambda: {"w": jax.device_put(arr)},
                           replica_id="sdc-dev")
        host = make_manager(state_dict=lambda: {"w": arr},
                            replica_id="sdc-host")
        try:
            assert dev._compute_state_digest() == \
                host._compute_state_digest()
        finally:
            dev.shutdown()
            host.shutdown()

    def test_attestation_off_yields_empty_digest(self):
        m = make_manager(attestation=False)
        try:
            assert m._compute_state_digest() == ""
            assert m.metrics()["sdc_digests_total"] == 0.0
        finally:
            m.shutdown()


# ------------------------------------------------------------ the vote


class TestAttestationVote:
    def _feed(self, agg, rows, now=NOW):
        for rid, kw in rows:
            agg.ingest(mk_digest(rid, **kw), now_ms=now)
        return agg.aggregate(now_ms=now + 10)

    def test_majority_quarantines_the_minority(self):
        agg = FleetAggregator()
        res = self._feed(agg, [
            ("a", dict(state_digest="aaaa")),
            ("b", dict(state_digest="aaaa")),
            ("c", dict(state_digest="cccc",
                       trace_addr="http://c:1/checkpoint/5")),
        ])
        assert sorted(agg.quarantined()) == ["c"]
        rec = agg.quarantined()["c"]
        assert rec["digest"] == "cccc"
        assert rec["majority_digest"] == "aaaa"
        assert rec["quorum_id"] == 1 and rec["step"] == 5
        f = res["fleet"]
        assert f["sdc_quarantined"] == ["c"]
        assert f["sdc_quarantined_addrs"] == ["http://c:1/checkpoint/5"]
        assert f["sdc_verdicts_total"] == 1
        by_id = {g["replica_id"]: g for g in res["groups"]}
        assert by_id["c"]["sdc_diverged"] and not by_id["a"]["sdc_diverged"]
        assert by_id["a"]["attested"]

    def test_fifty_fifty_split_fails_open(self):
        agg = FleetAggregator()
        self._feed(agg, [("a", dict(state_digest="aaaa")),
                         ("b", dict(state_digest="bbbb"))])
        assert agg.quarantined() == {}

    def test_two_vs_two_tie_fails_open(self):
        agg = FleetAggregator()
        self._feed(agg, [("a", dict(state_digest="aaaa")),
                         ("b", dict(state_digest="aaaa")),
                         ("c", dict(state_digest="cccc")),
                         ("d", dict(state_digest="cccc"))])
        assert agg.quarantined() == {}

    def test_healers_never_vote(self):
        """A mid-restore group's transient bytes are legitimately
        different; with the healer abstaining the remaining 2-1 vote
        still convicts the real minority — and a 1-1 remainder fails
        open."""
        agg = FleetAggregator()
        self._feed(agg, [
            ("a", dict(state_digest="aaaa")),
            ("b", dict(state_digest="aaaa")),
            ("h", dict(state_digest="hhhh", healing=True)),
            ("c", dict(state_digest="cccc")),
        ])
        assert sorted(agg.quarantined()) == ["c"]

    def test_absent_digest_and_foreign_quorum_abstain(self):
        agg = FleetAggregator()
        self._feed(agg, [
            ("a", dict(state_digest="aaaa")),
            ("b", dict(state_digest="aaaa")),
            ("n", dict(state_digest="")),           # pre-attestation
            ("q", dict(state_digest="qqqq", quorum_id=-1)),
        ])
        assert agg.quarantined() == {}  # 2 voters agree: no minority

    def test_different_steps_ballot_separately(self):
        """Ballots key on (quorum_id, step): a group one boundary
        behind must not be convicted against a different step's
        digests."""
        agg = FleetAggregator()
        self._feed(agg, [
            ("a", dict(step=5, state_digest="aaaa")),
            ("b", dict(step=5, state_digest="aaaa")),
            ("c", dict(step=4, state_digest="cccc")),
        ])
        assert agg.quarantined() == {}

    def test_verdict_is_sticky_and_counted_once(self):
        agg = FleetAggregator()
        rows = [("a", dict(state_digest="aaaa")),
                ("b", dict(state_digest="aaaa")),
                ("c", dict(state_digest="cccc"))]
        self._feed(agg, rows)
        # Same ballot re-aggregated: latched, not re-counted.
        for _ in range(3):
            self._feed(agg, rows)
        assert sorted(agg.quarantined()) == ["c"]
        assert agg.aggregate(now_ms=NOW + 50)["fleet"][
            "sdc_verdicts_total"] == 1

    def test_nonvoter_clear_on_match(self):
        """THE deadlock fix: a quarantined group reports
        ``healing=True`` (its own latch benched it), so its re-attested
        digest is never a ballot entry — but a fresh digest MATCHING
        the winner for the same ballot must clear it anyway, or the
        quarantine could never end."""
        agg = FleetAggregator()
        self._feed(agg, [("a", dict(state_digest="aaaa")),
                         ("b", dict(state_digest="aaaa")),
                         ("c", dict(state_digest="cccc"))])
        assert sorted(agg.quarantined()) == ["c"]
        res = self._feed(agg, [
            ("a", dict(step=6, state_digest="ffff")),
            ("b", dict(step=6, state_digest="ffff")),
            ("c", dict(step=6, state_digest="ffff", healing=True)),
        ], now=NOW + 1000)
        assert agg.quarantined() == {}
        assert res["fleet"]["sdc_clears_total"] == 1

    def test_still_divergent_reheal_stays_latched(self):
        agg = FleetAggregator()
        self._feed(agg, [("a", dict(state_digest="aaaa")),
                         ("b", dict(state_digest="aaaa")),
                         ("c", dict(state_digest="cccc"))])
        self._feed(agg, [
            ("a", dict(step=6, state_digest="ffff")),
            ("b", dict(step=6, state_digest="ffff")),
            ("c", dict(step=6, state_digest="0bad", healing=True)),
        ], now=NOW + 1000)
        assert sorted(agg.quarantined()) == ["c"]

    def test_farewell_clears_but_prune_does_not(self):
        agg = FleetAggregator()
        self._feed(agg, [("a", dict(state_digest="aaaa")),
                         ("b", dict(state_digest="aaaa")),
                         ("c", dict(state_digest="cccc"))])
        # Dead-without-farewell: rows age past stale_ms and prune out,
        # but the verdict stays — the corpse's last attested state is
        # still the corrupt one, and donor filters must keep excluding
        # its address if a cached copy resurfaces.
        agg.prune(now_ms=NOW + 10_000_000)
        assert sorted(agg.quarantined()) == ["c"]
        # A clean farewell DOES clear: the replacement rejoins behind
        # max_step and heals before it can attest anything.
        agg.remove("c")
        assert agg.quarantined() == {}

    def test_prometheus_exposition_names(self):
        agg = FleetAggregator()
        res = self._feed(agg, [("a", dict(state_digest="aaaa")),
                               ("b", dict(state_digest="aaaa")),
                               ("c", dict(state_digest="cccc"))])
        text = fleet.status_prometheus(res)
        assert "torchft_fleet_sdc_quarantined 1.0" in text
        assert "torchft_fleet_sdc_verdicts_total 1.0" in text


# ------------------------------------- satellite 1: read-time staleness


class TestReadTimeStaleness:
    def _cadenced(self, agg, rid, n, period_ms, t0=NOW, wall=100.0,
                  digest="aaaa", step0=0):
        for i in range(n):
            agg.ingest(mk_digest(rid, step=step0 + i, wall=wall,
                                 state_digest=digest),
                       now_ms=t0 + i * period_ms)
        return t0 + (n - 1) * period_ms

    def test_sigkilled_group_leaves_the_baseline(self):
        """The regression this satellite exists for: a SIGKILLed group
        (no farewell) kept feeding the straggler baseline with its last
        digest for the whole 60 s retention window. With the read-time
        bound (~2.5 median intervals, 2 s floor) it drops out of the
        baseline after ~2 missed boundaries while staying VISIBLE as
        ``stale``."""
        agg = FleetAggregator()
        t_dead = self._cadenced(agg, "dead", 8, 1000, wall=5000.0)
        # The live groups keep stepping well past the dead group.
        for rid in ("a", "b"):
            self._cadenced(agg, rid, 14, 1000, wall=100.0)
        now = t_dead + 6000  # 6 missed 1 s boundaries, well under 60 s
        res = agg.aggregate(now_ms=now)
        by_id = {g["replica_id"]: g for g in res["groups"]}
        assert by_id["dead"]["straggler_stage"] == "stale"
        assert not by_id["dead"]["baseline"]
        assert res["fleet"]["baseline_groups"] == 2
        # The huge dead wall must not crown the straggler.
        assert res["straggler"]["replica_id"] != "dead"

    def test_sparse_ring_falls_back_to_stale_ms(self):
        """Fewer than 2 observed intervals = no cadence estimate: the
        row stays baseline-eligible up to the hard stale_ms cut."""
        agg = FleetAggregator()
        agg.ingest(mk_digest("one", state_digest="aaaa"), now_ms=NOW)
        res = agg.aggregate(now_ms=NOW + 30_000)  # old, but < stale_ms
        assert res["groups"][0]["baseline"]

    def test_stale_rows_do_not_vote(self):
        """A dead group's divergent last digest must not convict it (or
        anyone): votes draw from FRESH rows only."""
        agg = FleetAggregator()
        self._cadenced(agg, "dead", 8, 1000, digest="dddd")
        t = self._cadenced(agg, "a", 14, 1000, digest="aaaa", step0=0)
        self._cadenced(agg, "b", 14, 1000, digest="aaaa", step0=0)
        # At now, dead's step-7 row is stale; a/b's step-13 rows are
        # fresh and unanimous. No ballot convicts dead.
        agg.aggregate(now_ms=t + 500)
        assert agg.quarantined() == {}

    def test_attested_flag_drops_with_freshness(self):
        agg = FleetAggregator()
        self._cadenced(agg, "dead", 8, 1000)
        for rid in ("a", "b"):
            self._cadenced(agg, rid, 14, 1000)
        res = agg.aggregate(now_ms=NOW + 13_500)
        by_id = {g["replica_id"]: g for g in res["groups"]}
        assert not by_id["dead"]["attested"]
        assert by_id["a"]["attested"]


# --------------------------- satellite 2: the shared donor predicate


class TestDonorAdmission:
    def _quarantine_bases(self, m, *bases):
        with m._metrics_lock:
            m._sdc_quarantined_bases = {_addr_base(b) for b in bases}

    def test_predicate_rules(self):
        m = make_manager()
        try:
            ok = "http://live:1/checkpoint/3"
            assert m._donor_admissible(ok)
            assert not m._donor_admissible("")
            assert not m._donor_admissible("", step_s="-1")
            assert not m._donor_admissible(ok, step_s="-1")
            assert not m._donor_admissible(ok, step_s="")
            assert not m._donor_admissible(ok, step_s="2", max_step=3)
            assert m._donor_admissible(ok, step_s="3", max_step=3)
            self._quarantine_bases(m, "http://live:1/checkpoint/9")
            # Base matching: ANY step suffix of a quarantined server is
            # inadmissible, and the ramckpt spelling too.
            assert not m._donor_admissible(ok, step_s="3", max_step=3)
            assert not m._donor_admissible("http://live:1/ramckpt/img")
            assert m._donor_admissible("http://other:1/checkpoint/3",
                                       step_s="3", max_step=3)
        finally:
            m.shutdown()

    def test_healset_donors_filter_quarantined(self):
        m = make_manager()
        store = FakeStore()
        store.set("torchft/healset/1", b"3:http://bad:1/checkpoint/3")
        store.set("torchft/healset/2", b"3:http://live:1/checkpoint/3")
        m._healset_store = ("s:1", store)
        self._quarantine_bases(m, "http://bad:1")
        try:
            q = quorum_result(max_step=3, replica_rank=0)
            donors = m._healset_donors(q, "http://primary:1/checkpoint/3")
            assert donors == ["http://primary:1/checkpoint/3",
                              "http://live:1/checkpoint/3"]
        finally:
            m.shutdown()

    def test_ram_peer_bases_filter_quarantined_and_tombstoned(self):
        m = make_manager()
        store = FakeStore()
        store.set("torchft/healset/1", b"-1:")  # withdrawn
        store.set("torchft/healset/2", b"4:http://bad:1/checkpoint/4")
        store.set("torchft/healset/3", b"4:http://live:1/checkpoint/4")
        m._healset_store = ("s:1", store)
        m._last_round_facts = ("s:1", 0, 4)
        self._quarantine_bases(m, "http://bad:1")
        try:
            assert m._ram_peer_bases() == ["http://live:1"]
        finally:
            m.shutdown()

    def test_resolve_checkpoint_addr_raises_on_quarantined_donor(self):
        m = make_manager()
        self._quarantine_bases(m, "http://bad:1")
        try:
            with MagicMock() as _:
                pass
            import torchft_tpu.manager as manager_mod
            real_client = manager_mod.ManagerClient
            fake = MagicMock()
            fake.return_value.checkpoint_address.return_value = \
                "http://bad:1/checkpoint/7"
            manager_mod.ManagerClient = fake
            try:
                with pytest.raises(RuntimeError, match="quarantined"):
                    m._resolve_checkpoint_addr("bad-manager:1")
            finally:
                manager_mod.ManagerClient = real_client
        finally:
            m.shutdown()


# -------------------------------------------- Manager quarantine ladder


class TestQuarantineLadder:
    def _verdict(self, sd, rids="", addrs=""):
        return quorum_result(sdc_diverged=sd, sdc_quarantined=rids,
                             sdc_quarantined_addrs=addrs)

    def test_latch_enters_the_full_ladder(self):
        store = FakeStore()
        store.set("torchft/healset/0", b"1:http://me:1/checkpoint/1")
        m = make_manager()
        m._healset_store = ("s:1", store)
        m._last_round_facts = ("s:1", 0, 3)
        m._flight = MagicMock()
        try:
            m._consume_fleet_hint(self._verdict(
                True, rids="sdc0",
                addrs="http://me:1/checkpoint/1"))
            assert m._sdc_quarantined
            assert not m.is_participating()  # zero-weight fold
            assert m._wire_weight() == 0
            # Advertisement withdrawn with the PR 14 tombstone.
            assert store.kv["torchft/healset/0"] == b"-1:"
            mx = m.metrics()
            assert mx["sdc_quarantined"] == 1.0
            assert mx["sdc_quarantines_total"] == 1.0
            assert m._flight.dump.call_args[0][0] == "sdc_divergence"
            events = [e["event"] for e in m.history()]
            assert "sdc_divergence" in events
            # The fleet lists landed for the donor filters.
            assert "sdc0" in m._sdc_quarantined_peers
            assert "http://me:1" in m._sdc_quarantined_bases
        finally:
            m.shutdown()

    def test_refusal_classes(self, tmp_path):
        m = make_manager()
        try:
            m._should_step = True  # a settled committed boundary...
            with m._metrics_lock:
                m._sdc_quarantined = True  # ...under a verdict
            writer = MagicMock()
            assert m.save_durable(writer, str(tmp_path)) is None
            assert not writer.save_async.called
            pub = MagicMock()
            assert m.publish(pub) is None
            assert not pub.publish.called
            m._ram_replicator = MagicMock()
            assert m.replicate_ram() is None
            assert not m._ram_replicator.replicate_async.called
            assert m.metrics()["sdc_refusals_total"] == 3.0
        finally:
            m._ram_replicator = None
            m.shutdown()

    def test_checkpoint_serve_gate_503(self):
        srv = CheckpointServer(
            lambda: {"user": {"w": np.ones(4, np.float32)},
                     "torchft": {"step": 1}},
            bind_host="127.0.0.1")
        try:
            srv.allow_checkpoint(1)
            addr = srv.address()
            assert urllib.request.urlopen(addr, timeout=10).status == 200
            srv.set_quarantined(True)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(addr, timeout=10)
            assert ei.value.code == 503
            srv.set_quarantined(False)
            assert urllib.request.urlopen(addr, timeout=10).status == 200
        finally:
            srv.shutdown()

    def test_absent_verdict_field_is_inert(self):
        """Duck-typed / pre-attestation control planes carry NO
        sdc_diverged attribute: neither a latch nor an all-clear."""
        m = make_manager()
        try:
            class Bare:
                pass

            m._consume_sdc_verdict(Bare())
            assert not m._sdc_quarantined
            with m._metrics_lock:
                m._sdc_quarantined = True
            m._consume_sdc_verdict(Bare())
            assert m._sdc_quarantined  # an old lighthouse never clears
        finally:
            m.shutdown()

    def test_clear_deferred_while_heal_in_flight(self):
        m = make_manager()
        try:
            m._consume_fleet_hint(self._verdict(True, rids="sdc0"))
            assert m._sdc_quarantined
            with m._metrics_lock:
                m._healing = True
            m._consume_fleet_hint(self._verdict(False))
            assert m._sdc_quarantined  # mid-heal all-clear must wait
            with m._metrics_lock:
                m._healing = False
            m._consume_fleet_hint(self._verdict(False))
            assert not m._sdc_quarantined
            assert m.is_participating()
            assert m.metrics()["sdc_quarantine_clears_total"] == 1.0
            events = [e["event"] for e in m.history()]
            assert "sdc_quarantine_clear" in events
        finally:
            m.shutdown()

    def test_reheal_with_no_admissible_donor_stays_latched(self):
        """Every advertised donor quarantined/tombstoned and no
        resolvable primary: stay zero-weighted and retry next boundary
        — healing from nothing beats healing from divergent bytes."""
        store = FakeStore()
        store.set("torchft/healset/1", b"-1:")
        store.set("torchft/healset/2", b"1:http://bad:1/checkpoint/1")
        m = make_manager()
        m._healset_store = ("s:1", store)
        with m._metrics_lock:
            m._sdc_quarantined = True
            m._sdc_quarantined_bases = {"http://bad:1"}
        try:
            m._sdc_reheal(quorum_result(recover_manager_address=""))
            assert m._sdc_quarantined
            assert m._pending_state_dict is None
            assert m.metrics()["sdc_reheals_total"] == 1.0
            assert m.metrics()["heal_count"] == 0.0  # no fetch started
        finally:
            m.shutdown()


# ----------------------------------- satellite 3: the chaos ``sdc`` band


class TestChaosSdcBand:
    def test_spec_parses_via_torchft_chaos_grammar(self):
        sched = chaos.parse_spec(
            "seed=7;sdc:sdc_flip_rate=0.25,max_faults=3")
        cfg = sched.config_for("sdc:g0")
        assert cfg is not None and cfg.sdc_flip_rate == 0.25
        assert cfg.max_faults == 3
        assert sched.config_for("ring:0") is None

    def test_env_activation(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_CHAOS", "seed=3;sdc:sdc_flip_rate=1.0")
        chaos.reset()
        try:
            d = chaos.sdc_fault("sdc:g0")
            assert d is not None and d.fault == "sdc_flip"
        finally:
            chaos.reset()

    def test_stream_purity_without_config(self):
        """No config for the sdc channel = NO decision draw: every
        other channel's fault sequence stays byte-identical with the
        band absent."""
        sched = ChaosSchedule(seed=1, endpoints={
            "ring": EndpointChaos(reset_rate=0.5)})
        assert chaos.sdc_fault("sdc:g0", schedule=sched) is None
        assert "sdc" not in sched._counts  # no stream was even opened

    def test_decision_determinism(self):
        mk = lambda: ChaosSchedule(seed=11, endpoints={  # noqa: E731
            "sdc": EndpointChaos(sdc_flip_rate=0.5)})
        a, b = mk(), mk()
        seq_a = [chaos.sdc_fault("sdc:g0", schedule=a) for _ in range(40)]
        seq_b = [chaos.sdc_fault("sdc:g0", schedule=b) for _ in range(40)]
        assert [(d.n, d.frac) if d else None for d in seq_a] == \
            [(d.n, d.frac) if d else None for d in seq_b]
        assert any(seq_a) and not all(seq_a)  # 0.5: mixed outcomes

    def test_intensity_scales_and_phased_chaos_composes(self):
        sched = ChaosSchedule(seed=5, endpoints={
            "sdc": EndpointChaos(sdc_flip_rate=1.0)})
        sched.set_intensity(0.0)  # storm over: rate 1.0 never fires
        assert all(chaos.sdc_fault("sdc:g0", schedule=sched) is None
                   for _ in range(20))
        # PhasedChaos drives the same knob: a terminal storm phase of
        # intensity 1.0 re-arms the band with no sdc-specific plumbing.
        PhasedChaos(sched, ((0.0, 0.0), (1000.0, 1.0))).tick()
        assert sched.intensity() == 1.0
        assert any(chaos.sdc_fault("sdc:g0", schedule=sched)
                   for _ in range(20))

    def test_max_faults_caps_the_band(self):
        sched = ChaosSchedule(seed=9, endpoints={
            "sdc": EndpointChaos(sdc_flip_rate=1.0, max_faults=1)})
        fired = [chaos.sdc_fault("sdc:g0", schedule=sched)
                 for _ in range(10)]
        assert sum(1 for d in fired if d) == 1

    def test_never_fires_on_a_healer_or_quarantined_group(self):
        """The injection contract: post-commit state, participants
        only. The Manager hook must not even DRAW while healing or
        latched — a flip there would corrupt a transient restore and
        model a fault the vote deliberately abstains on."""
        sched = ChaosSchedule(seed=1, endpoints={
            "sdc": EndpointChaos(sdc_flip_rate=1.0)})
        chaos.install(sched)
        m = make_manager()
        try:
            with m._metrics_lock:
                m._healing = True
            m._maybe_chaos_sdc()
            assert m.metrics()["sdc_chaos_flips_total"] == 0.0
            with m._metrics_lock:
                m._healing = False
                m._sdc_quarantined = True
            m._maybe_chaos_sdc()
            assert m.metrics()["sdc_chaos_flips_total"] == 0.0
            assert "sdc" not in sched._counts  # guarded before the draw
            with m._metrics_lock:
                m._sdc_quarantined = False
            m._maybe_chaos_sdc()  # a participant DOES flip
            assert m.metrics()["sdc_chaos_flips_total"] == 1.0
        finally:
            chaos.uninstall()
            m.shutdown()

    def test_flip_is_deterministic_and_single_bit(self):
        cell = {"w": np.arange(64, dtype=np.float32)}
        m = make_manager(state_dict=lambda: cell)
        m._user_load_state_dict = lambda s: (cell.clear(), cell.update(s))
        try:
            clean = cell["w"].copy()
            m._apply_sdc_flip(0.37)
            diff = cell["w"].view(np.uint8) ^ clean.view(np.uint8)
            changed = np.nonzero(diff)[0]
            assert changed.size == 1  # exactly one byte...
            assert bin(int(diff[changed[0]])).count("1") == 1  # ...one bit
            # Pure function of frac: the same draw reproduces the flip.
            cell["w"] = clean.copy()
            m._apply_sdc_flip(0.37)
            assert np.array_equal(cell["w"].view(np.uint8) ^
                                  clean.view(np.uint8), diff)
        finally:
            m.shutdown()

    def test_flip_changes_the_digest(self):
        cell = {"w": np.arange(64, dtype=np.float32)}
        m = make_manager(state_dict=lambda: cell)
        m._user_load_state_dict = lambda s: (cell.clear(), cell.update(s))
        try:
            clean = m._compute_state_digest()
            m._apply_sdc_flip(0.5)
            assert m._compute_state_digest() != clean
        finally:
            m.shutdown()


# ------------------------------------------------- the 3-group sdc soak


class SdcSoakHarness:
    """Three sync-mode Managers against a pure-Python lighthouse
    (:class:`FleetAggregator`): every round each group steps, then its
    committed-state digest is ingested exactly as the piggyback would
    carry it, and the NEXT round's quorum hints echo the aggregate's
    verdict lists — the full detection -> quarantine -> auto-heal ->
    clear loop with the real Manager and real checkpoint HTTP donors,
    no native toolchain."""

    RIDS = ("g0", "g1", "g2")

    def __init__(self):
        self.store = FakeStore()
        self.agg = FleetAggregator()
        self.now = NOW
        self.cells, self.mgrs, self.clients = {}, {}, {}
        self.verdicts = {}
        for i, rid in enumerate(self.RIDS):
            cell = {"w": np.arange(64, dtype=np.float32).copy(),
                    "b": np.ones(7, dtype=np.float32)}
            self.cells[rid] = cell
            client = MagicMock()
            client.quorum.return_value = self._qr(i, 1)
            client.should_commit.return_value = True
            self.clients[rid] = client
            m = make_manager(client=client, replica_id=rid,
                             state_dict=lambda _c=cell: _c)
            m._user_load_state_dict = \
                lambda s, _c=cell: (_c.clear(), _c.update(s))
            m._healset_store = ("s:1", self.store)
            self.mgrs[rid] = m

    def _qr(self, rank, step, **kw):
        return quorum_result(max_step=step, max_rank=2, replica_rank=rank,
                             **kw)

    def round(self, r):
        """One commit boundary across the fleet; returns the aggregate."""
        rids = ",".join(sorted(self.verdicts))
        addrs = ",".join(sorted(
            {rec.get("trace_addr", "")
             for rec in self.verdicts.values() if rec.get("trace_addr")}))
        for i, rid in enumerate(self.RIDS):
            self.clients[rid].quorum.return_value = self._qr(
                i, r, sdc_diverged=rid in self.verdicts,
                sdc_quarantined=rids, sdc_quarantined_addrs=addrs)
        # step() first for ALL groups: a quarantined group's re-heal
        # fetches from peers whose serve windows are open mid-step —
        # the same concurrency the async fleet has.
        for rid in self.RIDS:
            self.mgrs[rid].step()
        for rid in self.RIDS:
            m = self.mgrs[rid]
            if m.is_participating():
                m.allreduce({"g": np.ones(4, np.float32)}).result()
                m.should_commit()
        for rid in self.RIDS:
            m = self.mgrs[rid]
            self.agg.ingest(
                mk_digest(rid, step=r, state_digest=m._compute_state_digest(),
                          healing=bool(m._healing
                                       or not m.is_participating()),
                          trace_addr=m._ckpt_server.address()),
                now_ms=self.now)
        self.now += 1000
        res = self.agg.aggregate(now_ms=self.now)
        self.verdicts = self.agg.quarantined()
        return res

    def metrics(self, rid):
        return self.mgrs[rid].metrics()

    def bitwise_converged(self):
        ref = self.cells[self.RIDS[0]]
        return all(
            np.array_equal(ref[k], self.cells[rid][k])
            for rid in self.RIDS[1:] for k in ref)

    def shutdown(self):
        for m in self.mgrs.values():
            m.shutdown()


class TestSdcSoak:
    def _run(self, rounds, seed=42, max_faults=1, rate=1.0):
        h = SdcSoakHarness()
        sched = ChaosSchedule(seed=seed, endpoints={
            "sdc:g2": EndpointChaos(sdc_flip_rate=rate,
                                    max_faults=max_faults)})
        chaos.install(sched)
        timeline = []
        try:
            for r in range(1, rounds + 1):
                h.round(r)
                timeline.append(dict(
                    round=r,
                    flips=h.metrics("g2")["sdc_chaos_flips_total"],
                    verdicts=sorted(h.verdicts),
                    latched=h.mgrs["g2"]._sdc_quarantined,
                    reheals=h.metrics("g2")["sdc_reheals_total"],
                    clears=h.metrics("g2")["sdc_quarantine_clears_total"],
                ))
        finally:
            chaos.uninstall()
        return h, timeline

    def test_detect_quarantine_heal_converge(self):
        h, tl = self._run(6)
        try:
            # (1) Detection within ONE commit boundary of the flip.
            flip_round = next(t["round"] for t in tl if t["flips"])
            detect_round = next(t["round"] for t in tl if t["verdicts"])
            assert detect_round - flip_round <= 1
            assert tl[detect_round - 1]["verdicts"] == ["g2"]
            # (2) The ladder ran: latch + exactly one auto-reheal.
            assert any(t["latched"] for t in tl)
            assert tl[-1]["reheals"] == 1.0
            mx = h.metrics("g2")
            assert mx["sdc_quarantines_total"] == 1.0
            assert mx["heal_count"] == 1.0
            # (3) Quarantine fully cleared on both sides.
            assert tl[-1]["clears"] == 1.0
            assert not tl[-1]["latched"] and not tl[-1]["verdicts"]
            assert h.mgrs["g2"].is_participating()
            # (4) Bitwise fleet convergence.
            assert h.bitwise_converged()
            # (5) The healthy groups never latched.
            for rid in ("g0", "g1"):
                assert h.metrics(rid)["sdc_quarantines_total"] == 0.0
        finally:
            h.shutdown()

    def test_quarantined_round_refuses_persistence(self, tmp_path):
        h, tl = self._run(2)
        try:
            assert h.mgrs["g2"]._sdc_quarantined or \
                h.metrics("g2")["sdc_quarantine_clears_total"] >= 1.0
            # Re-latch deterministically to probe the refusal surface.
            with h.mgrs["g2"]._metrics_lock:
                h.mgrs["g2"]._sdc_quarantined = True
            h.mgrs["g2"]._should_step = True
            writer = MagicMock()
            assert h.mgrs["g2"].save_durable(writer, str(tmp_path)) is None
            assert not writer.save_async.called
        finally:
            h.shutdown()

    def test_clean_fleet_never_quarantines(self):
        h = SdcSoakHarness()
        try:
            for r in range(1, 5):
                h.round(r)
            assert h.agg.quarantined() == {}
            for rid in h.RIDS:
                assert h.metrics(rid)["sdc_quarantines_total"] == 0.0
            assert h.agg._sdc_verdicts_total == 0
        finally:
            h.shutdown()

    @pytest.mark.slow
    @pytest.mark.nightly
    def test_nightly_storm_soak(self):
        """Longer seeded round with a PhasedChaos-driven storm, then a
        chaos-free drain: repeated flips across phases, every verdict
        must heal and clear, and the fleet must end bitwise-converged
        with zero standing verdicts."""
        h = SdcSoakHarness()
        sched = ChaosSchedule(seed=1234, endpoints={
            "sdc:g2": EndpointChaos(sdc_flip_rate=0.6)})
        chaos.install(sched)
        phases = PhasedChaos(sched, ((0.0, 1.0), (3600.0, 1.0)))
        try:
            for r in range(1, 21):
                phases.tick()
                h.round(r)
        finally:
            chaos.uninstall()
        # Drain: with the storm over, the last verdict's reheal
        # re-attests clean and the non-voter clear-on-match fires.
        for r in range(21, 26):
            h.round(r)
        try:
            mx = h.metrics("g2")
            assert mx["sdc_chaos_flips_total"] >= 2.0
            assert mx["sdc_quarantines_total"] >= 1.0
            assert mx["sdc_quarantines_total"] == \
                mx["sdc_quarantine_clears_total"]
            # Drain: no flip fires while latched, so the last rounds
            # re-attest and the fleet settles clean.
            assert h.agg.quarantined() == {}
            assert not h.mgrs["g2"]._sdc_quarantined
            assert h.bitwise_converged()
            for rid in ("g0", "g1"):
                assert h.metrics(rid)["sdc_quarantines_total"] == 0.0
        finally:
            h.shutdown()
