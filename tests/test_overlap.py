"""Cross-step overlap engine tests (docs/design/overlap.md).

The delayed-gradient-application mode (``Manager(overlap_steps=1)`` +
:class:`~torchft_tpu.optim.DelayedOptimizer`): step N's cross-group
allreduce stays in flight across the step boundary, draining under step
N+1's compute, with the commit vote and optimizer update deferred to the
N+1 boundary. Four properties are pinned here, all tier-1 (no native
control plane — mocked clients, DummyCommunicator, and the socketpair
ring trick from test_manager):

* **State machine** — stage/settle ordering enforced, votes gate the
  step counter exactly as in sync mode, stale grads DROP on vote aborts
  and latched comm errors, ``save_durable`` refuses mid-flight
  snapshots, ``flush`` applies the final step.
* **Bitwise equivalence** — overlap-mode params after K steps equal the
  one-step-shifted schedule's (``θ_{k+1} = θ_k - u(avg ∇L(θ_{k-1},
  b_k))``) computed serially with the same jitted executables, for a
  single group and for two groups over a real socketpair ring — and
  through a mid-run heal (real HTTP checkpoint fetch), where the healer
  must land bitwise on the donor.
* **Failure paths** — a replica death mid-transfer latches, the vote
  aborts, and the survivor keeps exactly the last settled params.
* **Performance** — with comm time ~= compute time, overlap mode beats
  sync mode >= 1.5x on steps/s, and ``allreduce_hidden_ms_total``
  accounts for the gain (the acceptance A/B, run with a deterministic
  slowed ring so the assertion doesn't ride rig noise).

Plus the bf16 fetch-path regression guards: the cached jitted pack must
compile once per grad signature (``allreduce_pack_cache_misses`` frozen
after the first step) and non-native wire dtypes must cross D2H as
canonical uint bits (the BENCH_r05 regression fix).
"""

import threading
import time
from unittest.mock import MagicMock, patch

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import conftest  # noqa: F401  (forces the CPU platform)
from test_manager import (_make_test_rings, _wired_comm, make_manager,
                          quorum_result)
from torchft_tpu.backends.host import HostCommunicator
from torchft_tpu.communicator import DummyCommunicator
from torchft_tpu.manager import (Manager, _PACK_STATS, _pack_leaves,
                                 _transfer_dtype)
from torchft_tpu.optim import DelayedOptimizer
from torchft_tpu.parallel import FTTrainer

pytestmark = pytest.mark.overlap


def participant_client(world=2, **overrides):
    client = MagicMock()
    client.quorum.return_value = quorum_result(
        max_rank=overrides.pop("rank", 0), max_world_size=world,
        replica_rank=overrides.pop("replica_rank", 0),
        replica_world_size=world, **overrides)
    client.should_commit.return_value = True
    return client


class _Holder:
    def __init__(self, params, opt_state):
        self.params = params
        self.opt_state = opt_state


def _copy(tree):
    return jax.tree_util.tree_map(jnp.copy, tree)


class TestDeferredStateMachine:
    """The deferred-commit protocol at Manager + DelayedOptimizer level
    (mocked control plane, DummyCommunicator)."""

    def _setup(self, client=None, lr=1.0):
        client = client or participant_client()
        m = make_manager(client, overlap_steps=1)
        tx = optax.sgd(lr)
        opt = DelayedOptimizer(m, tx)
        params = {"g": jnp.asarray([2.0, 4.0], jnp.float32)}
        holder = _Holder(params, opt.init(params))
        return m, opt, holder, client

    def test_settle_applies_at_next_boundary(self):
        m, opt, holder, _ = self._setup()
        try:
            opt.begin_step()
            grads = {"g": np.asarray([2.0, 4.0], np.float32)}
            fut = m.allreduce(grads)
            opt.stage(holder, fut)
            assert opt.pending() and m.deferred_pending()
            assert m.deferred_step() == 1
            # Not applied yet: the update waits for the next boundary.
            np.testing.assert_array_equal(np.asarray(holder.params["g"]),
                                          [2.0, 4.0])
            assert opt.settle() is True
            # DummyComm returns the input; n=2 -> avg = [1, 2]; sgd(1.0).
            np.testing.assert_array_equal(np.asarray(holder.params["g"]),
                                          [1.0, 2.0])
            assert not opt.pending() and not m.deferred_pending()
            # The vote gated the NEXT advance, not the staged one.
            opt.begin_step()
            assert m.current_step() == 2
        finally:
            m.shutdown()

    def test_step_refuses_to_advance_over_unsettled_deferred(self):
        m, opt, holder, _ = self._setup()
        try:
            opt.begin_step()
            opt.stage(holder, m.allreduce({"g": np.zeros(2, np.float32)}))
            with pytest.raises(RuntimeError, match="deferred"):
                m.step()
            opt.settle()
            m.step()  # settled: advances normally
            assert m.current_step() == 2
        finally:
            m.shutdown()

    def test_vote_abort_drops_stale_grads(self):
        client = participant_client()
        client.should_commit.return_value = False
        m, opt, holder, _ = self._setup(client)
        try:
            opt.begin_step()
            before = np.asarray(holder.params["g"]).copy()
            opt.stage(holder, m.allreduce({"g": np.ones(2, np.float32)}))
            assert opt.settle() is False
            np.testing.assert_array_equal(np.asarray(holder.params["g"]),
                                          before)  # dropped, not applied
            mx = m.metrics()
            assert mx["overlap_grads_dropped"] == 1
            assert mx["aborted_steps"] == 1
            # Abort: the step counter must not advance.
            client.should_commit.return_value = True
            opt.begin_step()
            assert m.current_step() == 1
        finally:
            m.shutdown()

    def test_latched_comm_error_drops_stale_grads(self):
        client = participant_client()
        client.should_commit.return_value = False
        comm = DummyCommunicator()
        m = make_manager(client, comm, overlap_steps=1)
        opt = DelayedOptimizer(m, optax.sgd(1.0))
        params = {"g": jnp.ones(2, jnp.float32)}
        holder = _Holder(params, opt.init(params))
        try:
            opt.begin_step()
            comm.allreduce = MagicMock(side_effect=RuntimeError("boom"))
            before = np.asarray(holder.params["g"]).copy()
            opt.stage(holder, m.allreduce({"g": np.ones(2, np.float32)}))
            assert m.errored() is not None  # latched while in flight
            assert opt.settle() is False
            np.testing.assert_array_equal(np.asarray(holder.params["g"]),
                                          before)
            assert m.metrics()["overlap_grads_dropped"] == 1
        finally:
            m.shutdown()

    def test_save_durable_refuses_mid_flight_then_saves_after_flush(self):
        m, opt, holder, _ = self._setup()
        writer = MagicMock()
        writer.save_async.return_value = "fut"
        try:
            opt.begin_step()
            opt.stage(holder, m.allreduce({"g": np.zeros(2, np.float32)}))
            # Mid-flight: manager metadata (step advanced) and params
            # (update unapplied) describe different steps — refused.
            assert m.save_durable(writer, "/tmp/nowhere") is None
            assert m.metrics()["ckpt_save_skipped"] == 1
            writer.save_async.assert_not_called()
            assert opt.flush() is True
            assert m.save_durable(writer, "/tmp/nowhere") == "fut"
            writer.save_async.assert_called_once()
        finally:
            m.shutdown()

    def test_flush_none_when_nothing_pending(self):
        m, opt, holder, _ = self._setup()
        try:
            assert opt.flush() is None
        finally:
            m.shutdown()

    def test_overlap_metrics_populate_and_inflight_drains(self):
        m, opt, holder, _ = self._setup()
        try:
            for _ in range(3):
                opt.flush()
                opt.begin_step()
                opt.stage(holder,
                          m.allreduce({"g": np.ones(2, np.float32)}))
            opt.flush()
            mx = m.metrics()
            assert mx["overlap_steps_deferred"] == 3
            assert mx["allreduce_hidden_ms_total"] >= 0.0
            assert mx["allreduce_drain_wait_ms_total"] >= 0.0
            assert mx["allreduce_inflight"] == 0  # all drained
        finally:
            m.shutdown()

    def test_overlap_steps_validation(self):
        with pytest.raises(ValueError, match="overlap_steps"):
            make_manager(participant_client(), overlap_steps=2)


class TestOverlapEquivalence:
    """Bitwise equivalence with the one-step-shifted schedule: the
    overlap engine's params after K steps must equal the serial oracle
    θ_{k+1} = θ_k - u(avg_g ∇L_g(θ_{k-1}, b_{g,k})) computed with the
    SAME jitted executables (grads evaluated one update behind — the
    documented staleness)."""

    K = 6

    @staticmethod
    def _loss_fn(params, batch):
        return jnp.mean((batch["x"] @ params["w"] - batch["y"]) ** 2)

    @classmethod
    def _params0(cls):
        return {"w": jnp.zeros((4,), jnp.float32)}

    @classmethod
    def _batches(cls, group, k):
        rng = np.random.default_rng(100 * group + k)
        return {"x": jnp.asarray(rng.normal(size=(8, 4)), jnp.float32),
                "y": jnp.asarray(rng.normal(size=(8,)), jnp.float32)}

    def _trainer(self, client, comm, overlap):
        return FTTrainer(
            loss_fn=self._loss_fn, tx=optax.sgd(0.1),
            params=self._params0(),
            manager_factory=lambda load, save: Manager(
                comm=comm, load_state_dict=load, state_dict=save,
                min_replica_size=1, rank=0, world_size=1,
                replica_id="eq", overlap_steps=overlap,
                _manager_client=client),
        )

    def test_single_group_matches_shifted_oracle(self):
        client = participant_client(world=1)
        tr = self._trainer(client, DummyCommunicator(), overlap=1)
        try:
            for k in range(self.K):
                tr.train_step(self._batches(0, k))
            assert tr.flush() is True
            got = np.asarray(tr.params["w"])
            fwd, upd = tr._fwd_bwd, tr._opt._ft._update
        finally:
            tr.shutdown()

        # Serial oracle of the delayed schedule, same executables.
        P, O = self._params0(), optax.sgd(0.1).init(self._params0())
        staged = None
        for k in range(self.K):
            _, _, g = fwd(P, None, self._batches(0, k))  # stale point
            if staged is not None:
                P, O = upd(_copy(P), _copy(O), staged)
            staged = g
        P, O = upd(_copy(P), _copy(O), staged)
        assert np.asarray(P["w"]).tobytes() == got.tobytes()

        # Sanity: the shifted schedule genuinely differs from sync mode.
        tr2 = self._trainer(participant_client(world=1),
                            DummyCommunicator(), overlap=0)
        try:
            for k in range(self.K):
                tr2.train_step(self._batches(0, k))
            assert np.asarray(tr2.params["w"]).tobytes() != got.tobytes()
        finally:
            tr2.shutdown()

    def test_two_groups_ring_bitwise_vs_shifted_oracle(self):
        """Two groups over a REAL socketpair ring. Single-threaded
        alternation is deliberate: within one iteration A's settle
        drains step k-1 (B contributed last iteration) and B's settle
        drains after A already contributed this iteration — the
        deferred engine never blocks inside an iteration, which is
        itself a property under test."""
        rings = _make_test_rings(2)
        trainers = [
            self._trainer(
                participant_client(world=2, rank=r, replica_rank=r),
                _wired_comm(rings[r], r, 2), overlap=1)
            for r in range(2)
        ]
        try:
            for k in range(self.K):
                for r in (0, 1):
                    trainers[r].train_step(self._batches(r, k))
            for r in (0, 1):
                assert trainers[r].flush() is True
            got = [np.asarray(t.params["w"]) for t in trainers]
            fwd, upd = trainers[0]._fwd_bwd, trainers[0]._opt._ft._update
            mx = trainers[0].manager.metrics()
        finally:
            for t in trainers:
                t.shutdown()
            for ring in rings:
                ring.close()

        # Lockstep across groups first.
        assert got[0].tobytes() == got[1].tobytes()
        # Deferred accounting populated on the real ring.
        assert mx["overlap_steps_deferred"] == self.K
        assert mx["overlap_grads_dropped"] == 0

        # Serial shifted-schedule oracle; the exact-mode world-2 ring is
        # bitwise a two-term sum, and /2 is exact in f32.
        P, O = self._params0(), optax.sgd(0.1).init(self._params0())
        staged = None
        for k in range(self.K):
            gs = [fwd(P, None, self._batches(r, k))[2] for r in (0, 1)]
            if staged is not None:
                P, O = upd(_copy(P), _copy(O), staged)
            staged = jax.tree_util.tree_map(
                lambda a, b: (a + b) / 2, *gs)
        P, O = upd(_copy(P), _copy(O), staged)
        assert np.asarray(P["w"]).tobytes() == got[0].tobytes()

    def test_bitwise_through_midrun_heal(self):
        """Mid-run heal under overlap: group B's params are scrambled,
        its next quorum marks it a healer, and the REAL checkpoint
        transport (HTTP fetch from A's live state, served during A's
        open heal window) restores it; B then applies the RECEIVED
        average to the restored state at its settle — landing bitwise on
        A. Also exercises the engine's recompute path: B's speculative
        forward/backward at pre-heal params is discarded."""
        heal_at = 3  # 1-indexed step at which B heals
        K = 6

        def b_quorum(step):
            if step == heal_at:
                return quorum_result(
                    max_rank=None, max_world_size=1, replica_rank=1,
                    replica_world_size=2, heal=True, max_step=heal_at,
                    recover_manager_address="managerA")
            world = 1 if step == heal_at else 2
            # After the heal step both participate again.
            return quorum_result(
                max_rank=1, max_world_size=2, replica_rank=1,
                replica_world_size=2)

        def a_quorum(step):
            if step == heal_at:
                # B is healing: A is the only participant this step.
                return quorum_result(max_rank=0, max_world_size=1,
                                     replica_rank=0,
                                     replica_world_size=2)
            return quorum_result(max_rank=0, max_world_size=2,
                                 replica_rank=0, replica_world_size=2)

        client_a, client_b = MagicMock(), MagicMock()
        client_a.quorum.side_effect = [a_quorum(s)
                                       for s in range(1, K + 1)]
        client_b.quorum.side_effect = [b_quorum(s)
                                       for s in range(1, K + 1)]
        client_a.should_commit.return_value = True
        client_b.should_commit.return_value = True

        rings = _make_test_rings(2)
        tr_a = self._trainer(client_a, _wired_comm(rings[0], 0, 2), 1)
        tr_b = self._trainer(client_b, _wired_comm(rings[1], 1, 2), 1)

        def make_primary(addr, **kwargs):
            mc = MagicMock()
            mc.checkpoint_address.return_value = \
                tr_a.manager._ckpt_server.address()
            return mc

        try:
            with patch("torchft_tpu.manager.ManagerClient",
                       side_effect=make_primary):
                for k in range(K):
                    if k + 1 == heal_at:
                        # Scramble B: the heal must restore it.
                        tr_b.params = jax.tree_util.tree_map(
                            lambda a: a * 0 - 3.0, tr_b.params)
                    tr_a.train_step(self._batches(0, k))
                    tr_b.train_step(self._batches(1, k))
                assert tr_a.flush() is True
                assert tr_b.flush() is True
            pa = np.asarray(tr_a.params["w"])
            pb = np.asarray(tr_b.params["w"])
            mb = tr_b.manager.metrics()
        finally:
            tr_a.shutdown()
            tr_b.shutdown()
            for ring in rings:
                ring.close()

        assert mb["heal_count"] == 1
        assert mb["heal_bytes_total"] > 0  # real HTTP transfer happened
        assert pa.tobytes() == pb.tobytes()

    def test_sync_quorum_heal_recomputes_at_restored_params(self):
        """use_async_quorum=False heals restore INSIDE ``step()`` (and
        clear the healing flag there), after the overlap loop's
        speculative dispatch: the params-identity guard must detect the
        restore and recompute, or the healer would contribute grads
        computed at its pre-heal garbage params as a full participant."""
        heal_at, K = 3, 5

        def quorums(rank):
            out = []
            for s in range(1, K + 1):
                if s == heal_at and rank == 1:
                    out.append(quorum_result(
                        max_rank=1, max_world_size=2, replica_rank=1,
                        replica_world_size=2, heal=True, max_step=s,
                        recover_manager_address="managerA"))
                else:
                    out.append(quorum_result(
                        max_rank=rank, max_world_size=2,
                        replica_rank=rank, replica_world_size=2))
            return out

        rings = _make_test_rings(2)
        trainers = []
        for r in (0, 1):
            client = MagicMock()
            client.quorum.side_effect = quorums(r)
            client.should_commit.return_value = True
            trainers.append(FTTrainer(
                loss_fn=self._loss_fn, tx=optax.sgd(0.1),
                params=self._params0(),
                manager_factory=lambda load, save, r=r, c=client: Manager(
                    comm=_wired_comm(rings[r], r, 2), load_state_dict=load,
                    state_dict=save, min_replica_size=1, rank=0,
                    world_size=1, replica_id=f"sq{r}", overlap_steps=1,
                    use_async_quorum=False, _manager_client=c)))
        tr_a, tr_b = trainers

        # Spy on B's forward/backward: record (iteration, param sum) so
        # the recompute at restored params is directly observable.
        calls = []
        iter_cell = {"k": -1}
        orig_fwd = tr_b._fwd_bwd

        def spy(p, st, b):
            calls.append((iter_cell["k"], float(jnp.sum(p["w"]))))
            return orig_fwd(p, st, b)

        tr_b._fwd_bwd = spy

        def make_primary(addr, **kwargs):
            mc = MagicMock()
            mc.checkpoint_address.return_value = \
                tr_a.manager._ckpt_server.address()
            return mc

        SCRAMBLE = -9000.0
        try:
            with patch("torchft_tpu.manager.ManagerClient",
                       side_effect=make_primary):
                for k in range(K):
                    iter_cell["k"] = k
                    if k + 1 == heal_at:
                        tr_b.params = jax.tree_util.tree_map(
                            lambda a: a * 0 + SCRAMBLE, tr_b.params)
                    tr_a.train_step(self._batches(0, k))
                    tr_b.train_step(self._batches(1, k))
                assert tr_a.flush() is True
                assert tr_b.flush() is True
            pa = np.asarray(tr_a.params["w"])
            pb = np.asarray(tr_b.params["w"])
            assert tr_b.manager.metrics()["heal_count"] == 1
        finally:
            tr_a.shutdown()
            tr_b.shutdown()
            for ring in rings:
                ring.close()

        assert pa.tobytes() == pb.tobytes()
        heal_iter = [s for it, s in calls if it == heal_at - 1]
        # Speculative dispatch saw the scrambled params...
        assert abs(heal_iter[0]) > 1000, calls
        # ...and the post-restore recompute (the grads actually
        # contributed) ran at the RESTORED params, not the garbage.
        assert len(heal_iter) >= 2, calls
        assert abs(heal_iter[-1]) < 100, calls


class TestReplicaDeathMidFlight:
    """In-flight deferred allreduce + replica death: the transfer
    errors, the error latches, the deferred vote aborts, and the
    survivor's params stay EXACTLY at the last settled state (the same
    state sync mode recovers to — dropped, never half-applied)."""

    def test_survivor_drops_stale_grads_and_keeps_last_state(self):
        loss_fn = TestOverlapEquivalence._loss_fn
        params0 = TestOverlapEquivalence._params0()
        batches = TestOverlapEquivalence._batches
        rings = _make_test_rings(2)

        def trainer(r, client):
            return FTTrainer(
                loss_fn=loss_fn, tx=optax.sgd(0.1), params=params0,
                manager_factory=lambda load, save: Manager(
                    comm=_wired_comm(rings[r], r, 2), load_state_dict=load,
                    state_dict=save, min_replica_size=1, rank=0,
                    world_size=1, replica_id=f"death{r}", overlap_steps=1,
                    _manager_client=client),
            )

        client_a = participant_client(world=2, rank=0, replica_rank=0)
        client_b = participant_client(world=2, rank=1, replica_rank=1)
        tr_a = trainer(0, client_a)
        tr_b = trainer(1, client_b)
        try:
            for k in range(2):
                tr_a.train_step(batches(0, k))
                tr_b.train_step(batches(1, k))
            # Iteration 3: A settles step 2 and stages step 3...
            tr_a.train_step(batches(0, 2))
            settled = np.asarray(tr_a.params["w"]).copy()
            # ...then B dies mid-transfer (never contributes step 3).
            tr_b.manager.shutdown()
            # The step-3 vote must abort (a real barrier would return
            # False; the mock mirrors that).
            client_a.should_commit.return_value = False
            assert tr_a.flush() is False
            assert tr_a.manager.errored() is not None
            # Stale grads dropped: params are exactly the last settled
            # state, bitwise.
            assert np.asarray(tr_a.params["w"]).tobytes() \
                == settled.tobytes()
            mx = tr_a.manager.metrics()
            assert mx["overlap_grads_dropped"] == 1
            assert mx["aborted_steps"] == 1
            # Abort semantics unchanged: the survivor holds at step 3
            # (the aborted step), poised to retry it.
            assert tr_a.manager.current_step() == 3
        finally:
            tr_a.shutdown()
            for ring in rings:
                ring.close()


class _SlowWiredComm(HostCommunicator):
    """Socketpair-wired host communicator whose wire collective costs a
    deterministic extra delay on the op worker — comm-bound conditions
    without rig-dependent payloads."""

    def __init__(self, ring, rank, world, delay):
        super().__init__(timeout_sec=30)
        self._ring, self._rank, self._world = ring, rank, world
        self._delay = delay

    def configure(self, store_addr, rank, world_size):
        pass  # pre-wired

    def _do_allreduce_wire(self, *args, **kwargs):
        time.sleep(self._delay)
        return super()._do_allreduce_wire(*args, **kwargs)


class TestOverlapPerfAB:
    """The acceptance A/B: with comm ~= compute, deferring the drain
    must buy >= 1.5x steps/s over the sync protocol, and
    ``allreduce_hidden_ms_total`` must account for the gain. The ring
    is slowed deterministically (sleep on the comm worker) so the
    assertion tests the ENGINE, not the rig."""

    COMPUTE_S = 0.15
    COMM_S = 0.15
    STEPS = 6

    def _run(self, overlap: bool) -> dict:
        rings = _make_test_rings(2)
        walls = [None] * 2
        hidden = [0.0] * 2
        errors = []
        tree = {"g": np.ones(1024, np.float32)}
        tx = optax.sgd(0.0)

        def run(rank):
            client = participant_client(world=2, rank=rank,
                                        replica_rank=rank)
            m = make_manager(
                client,
                comm=_SlowWiredComm(rings[rank], rank, 2, self.COMM_S),
                overlap_steps=1 if overlap else 0)
            from torchft_tpu.optim import FTOptimizer

            params = {"g": jnp.ones(1024, jnp.float32)}
            try:
                if overlap:
                    opt = DelayedOptimizer(m, tx)
                    holder = _Holder(params, opt.init(params))
                    t0 = None
                    for k in range(self.STEPS + 1):
                        time.sleep(self.COMPUTE_S)  # "compute"
                        if opt.pending():
                            assert opt.settle()
                        if k == 1:
                            t0 = time.perf_counter()  # past compiles
                        opt.begin_step()
                        opt.stage(holder, m.allreduce(dict(tree)))
                    assert opt.flush()
                else:
                    opt = FTOptimizer(m, tx)
                    holder = _Holder(params, opt.init(params))
                    t0 = None
                    for k in range(self.STEPS + 1):
                        if k == 1:
                            t0 = time.perf_counter()
                        m.step()
                        time.sleep(self.COMPUTE_S)
                        avg = m.allreduce(dict(tree)).result()
                        assert opt.apply(holder, avg)
                walls[rank] = time.perf_counter() - t0
                hidden[rank] = m.metrics()["allreduce_hidden_ms_total"]
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                m.shutdown()

        threads = [threading.Thread(target=run, args=(r,))
                   for r in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        for ring in rings:
            ring.close()
        assert not errors, errors
        assert all(w is not None for w in walls)
        return {"steps_per_s": self.STEPS / max(walls),
                "wall": max(walls), "hidden_ms": max(hidden)}

    def test_overlap_beats_sync_1p5x_and_hidden_accounts(self):
        sync = self._run(overlap=False)
        ov = self._run(overlap=True)
        speedup = ov["steps_per_s"] / sync["steps_per_s"]
        assert speedup >= 1.5, (sync, ov)
        # The gain is the hidden comm: the hidden counter must cover
        # most of the wall-clock saved (slack for scheduling jitter).
        saved_ms = (sync["wall"] - ov["wall"]) * 1e3
        assert ov["hidden_ms"] >= 0.6 * saved_ms, (ov, saved_ms)
        assert sync["hidden_ms"] == 0.0  # sync mode never defers


class TestPackFetchPath:
    """bf16 wire fetch regression guards (BENCH_r05: 12.9s vs 2.9s
    fetch at HALF the bytes): the pack executable must compile once per
    grad signature, and non-native wire dtypes must cross D2H as
    canonical uint bits (custom ml_dtypes buffers can fall off the
    runtime's raw-bytes transfer fast path onto a per-element
    conversion path)."""

    def test_pack_bitcasts_custom_wire_dtype_to_canonical_carrier(self):
        assert _transfer_dtype(np.float32) is None
        assert _transfer_dtype(np.float64) is None
        assert _transfer_dtype(jnp.bfloat16) == np.dtype(np.uint16)

        x = jnp.asarray(
            np.random.default_rng(0).normal(size=(37,)), jnp.float32)
        packed = _pack_leaves([x], "bfloat16")
        # Canonical carrier on the wire-transfer leg...
        assert packed.dtype == jnp.uint16
        got = np.asarray(jax.device_get(packed)).view(
            np.dtype(jnp.bfloat16))
        want = np.asarray(jax.device_get(x.astype(jnp.bfloat16)))
        # ...and a bitwise-identical payload after the host-side view.
        assert got.tobytes() == want.tobytes()
        # Native dtypes are untouched.
        assert _pack_leaves([x], "float32").dtype == jnp.float32

    def test_zero_pack_cache_misses_after_first_step(self):
        """Three pipelined bf16-wire steps over a real ring: the pack
        (and schedule) caches must make steps 2..3 compile-free —
        ``allreduce_pack_cache_misses`` frozen after step 1. A per-step
        retrace here is the silent 10x fetch collapse failure mode."""
        world, steps = 2, 3
        rings = _make_test_rings(world)
        miss_log: list = []
        barrier = threading.Barrier(world)
        errors = []
        base = np.random.default_rng(0).normal(size=(600,)).astype(
            np.float32)

        def run(rank):
            client = participant_client(world=world, rank=rank,
                                        replica_rank=rank)
            m = make_manager(client,
                             comm=_wired_comm(rings[rank], rank, world),
                             allreduce_bucket_bytes=512,
                             allreduce_wire_dtype=jnp.bfloat16)
            try:
                for s in range(steps):
                    m.step()
                    tree = {"g": jnp.asarray(base * (rank + 1 + s))}
                    m.allreduce(tree).result(timeout=30)
                    assert m.errored() is None, m.errored()
                    assert m.should_commit()
                    barrier.wait(timeout=30)
                    if rank == 0:
                        miss_log.append(
                            m.metrics()["allreduce_pack_cache_misses"])
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            finally:
                m.shutdown()

        threads = [threading.Thread(target=run, args=(r,))
                   for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        for ring in rings:
            ring.close()
        assert not errors, errors
        assert len(miss_log) == steps
        # Whatever compiled on step 1, steps 2..N must add NOTHING.
        assert miss_log[0] == miss_log[-1], miss_log
