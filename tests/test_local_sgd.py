"""DiLoCo local-SGD tests: unit (mocked manager) + 2-group integration."""

from concurrent.futures import Future, ThreadPoolExecutor
from unittest.mock import MagicMock

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchft_tpu import HostCommunicator, Lighthouse, Manager
from torchft_tpu.local_sgd import DiLoCoTrainer


def echo_allreduce(tree):
    f: Future = Future()
    f.set_result(tree)
    return f


def make_trainer(manager, sync_every=4):
    def loss_fn(params, batch):
        return jnp.mean((params["w"] - batch) ** 2)

    return DiLoCoTrainer(
        loss_fn=loss_fn,
        inner_tx=optax.sgd(0.1),
        params={"w": jnp.zeros(4)},
        manager_factory=lambda load, save: manager,
        sync_every=sync_every,
        jit=False,
    )


class TestDiLoCoUnit:
    def test_outer_round_cadence(self):
        manager = MagicMock()
        manager.should_commit.return_value = True
        manager.allreduce.side_effect = echo_allreduce
        t = make_trainer(manager, sync_every=4)
        target = jnp.full(4, 1.0)
        for i in range(4):
            _, committed = t.train_step(target)
            assert committed is (True if (i + 1) % 4 == 0 else None)
        assert manager.step.call_count == 1
        # right after the round, local params reset to the new anchor
        np.testing.assert_allclose(np.asarray(t.params["w"]),
                                   np.asarray(t.anchor["w"]))
        for i in range(3):
            _, committed = t.train_step(target)
            assert committed is None
        assert manager.step.call_count == 1  # still one outer round
        # inner steps moved local params off the anchor
        assert not np.allclose(np.asarray(t.params["w"]),
                               np.asarray(t.anchor["w"]))

    def test_inner_steps_do_not_communicate(self):
        manager = MagicMock()
        manager.allreduce.side_effect = echo_allreduce
        manager.should_commit.return_value = True
        t = make_trainer(manager, sync_every=100)
        for _ in range(50):
            t.train_step(jnp.ones(4))
        manager.step.assert_not_called()
        manager.allreduce.assert_not_called()

    def test_failed_round_keeps_local_progress(self):
        manager = MagicMock()
        manager.allreduce.side_effect = echo_allreduce
        manager.should_commit.return_value = False
        t = make_trainer(manager, sync_every=2)
        t.train_step(jnp.ones(4))
        params_before = np.asarray(t.params["w"])
        anchor_before = np.asarray(t.anchor["w"])
        _, committed = t.train_step(jnp.ones(4))
        assert committed is False
        # anchor untouched; local params kept training (≠ reset)
        np.testing.assert_allclose(np.asarray(t.anchor["w"]), anchor_before)
        assert not np.allclose(np.asarray(t.params["w"]), anchor_before)
        assert not np.allclose(np.asarray(t.params["w"]), params_before)

    def test_outer_applies_averaged_delta(self):
        manager = MagicMock()
        manager.should_commit.return_value = True
        # pretend the other group moved twice as far: average given back
        manager.allreduce.side_effect = echo_allreduce
        t = make_trainer(manager, sync_every=1)
        _, committed = t.train_step(jnp.full(4, 10.0))
        assert committed
        # outer sgd(0.7, nesterov 0.9): anchor moved toward params
        assert 0 < float(np.asarray(t.anchor["w"]).mean())


@pytest.mark.integration
class TestDiLoCoIntegration:
    def test_two_groups_converge_identically(self):
        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=2,
                        join_timeout_ms=1000, quorum_tick_ms=50)

        def run_group(group):
            def loss_fn(params, batch):
                return jnp.mean((params["w"] - batch) ** 2)

            t = DiLoCoTrainer(
                loss_fn=loss_fn,
                inner_tx=optax.sgd(0.05),
                params={"w": jnp.zeros(4)},
                manager_factory=lambda load, save: Manager(
                    comm=HostCommunicator(timeout_sec=15),
                    load_state_dict=load,
                    state_dict=save,
                    min_replica_size=2,
                    replica_id=f"diloco{group}",
                    lighthouse_addr=lh.address(),
                    rank=0, world_size=1,
                    timeout_ms=15_000, quorum_timeout_ms=15_000,
                ),
                sync_every=3,
            )
            # groups chase different targets; outer rounds reconcile
            target = jnp.full(4, float(group + 1))
            try:
                while t.manager.current_step() < 3:  # 3 outer rounds
                    t.train_step(target)
                return jax.device_get(t.anchor)
            finally:
                t.shutdown()

        try:
            with ThreadPoolExecutor(max_workers=2) as pool:
                futs = [pool.submit(run_group, g) for g in range(2)]
                results = [f.result(timeout=120) for f in futs]
        finally:
            lh.shutdown()
        np.testing.assert_array_equal(results[0]["w"], results[1]["w"])
        assert float(results[0]["w"].mean()) > 0  # moved off init
