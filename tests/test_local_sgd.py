"""DiLoCo local-SGD tests: unit (mocked manager) + 2-group integration."""

from concurrent.futures import Future, ThreadPoolExecutor
from unittest.mock import MagicMock

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchft_tpu import HostCommunicator, Lighthouse, Manager
from torchft_tpu.local_sgd import DiLoCoTrainer, StreamingDiLoCoTrainer


class FakeManager:
    """Stateful stand-in for the streaming schedule tests: echo allreduce,
    always-commit, commit-gated step counter like the real Manager."""

    def __init__(self):
        self.step_calls = 0
        self.allreduce_calls = 0
        self._step = 0
        self._should_step = True
        self.commit_result = True

    def step(self):
        self.step_calls += 1
        if self._should_step:
            self._step += 1

    def wait_quorum(self):
        pass

    def current_step(self):
        return self._step

    def allreduce(self, tree):
        self.allreduce_calls += 1
        return echo_allreduce(tree)

    def should_commit(self):
        self._should_step = self.commit_result
        return self.commit_result

    def is_healing(self):
        return False

    def shutdown(self):
        pass


def echo_allreduce(tree):
    f: Future = Future()
    f.set_result(tree)
    return f


def make_trainer(manager, sync_every=4):
    def loss_fn(params, batch):
        return jnp.mean((params["w"] - batch) ** 2)

    return DiLoCoTrainer(
        loss_fn=loss_fn,
        inner_tx=optax.sgd(0.1),
        params={"w": jnp.zeros(4)},
        manager_factory=lambda load, save: manager,
        sync_every=sync_every,
        jit=False,
    )


def make_streaming(manager, sync_every=4, fragments=2):
    def loss_fn(params, batch):
        return (jnp.mean((params["w"] - batch) ** 2)
                + jnp.mean((params["b"] - batch[:2]) ** 2))

    return StreamingDiLoCoTrainer(
        loss_fn=loss_fn,
        inner_tx=optax.sgd(0.1),
        params={"b": jnp.zeros(2), "w": jnp.zeros(4)},
        manager_factory=lambda load, save: manager,
        sync_every=sync_every,
        fragments=fragments,
        jit=False,
    )


class TestStreamingUnit:
    def test_schedule_launch_collect_overlap(self):
        """Every interval: collect the in-flight fragment (None on the
        first), launch the next. Rounds = launches; commits lag launches
        by one interval — the overlap."""
        fm = FakeManager()
        t = make_streaming(fm, sync_every=4, fragments=2)  # interval 2
        target = jnp.full(4, 1.0)
        seen = [t.train_step(target)[1] for _ in range(8)]
        assert seen == [None, None, None, True, None, True, None, True]
        assert fm.step_calls == 4  # launches at steps 2, 4, 6, 8
        assert fm.allreduce_calls == 4
        assert t._pending is not None  # one round always in flight
        assert t.flush() is True
        assert t._pending is None

    def test_fragments_rotate_with_round_counter(self):
        fm = FakeManager()
        t = make_streaming(fm, sync_every=4, fragments=2)
        target = jnp.full(4, 1.0)
        frags = []
        for _ in range(4):
            t.train_step(target)
            t.train_step(target)
            frags.append(t._pending[0])
        assert frags == [1, 0, 1, 0]  # round % fragments

    def test_only_synced_fragment_anchor_moves(self):
        fm = FakeManager()
        t = make_streaming(fm, sync_every=4, fragments=2)
        target = jnp.full(4, 1.0)
        for _ in range(2):
            t.train_step(target)   # launch frag 1 (round 1)
        frag = t._pending[0]
        anchor_before = jax.device_get(t.anchor)
        for _ in range(2):
            t.train_step(target)   # collect frag `frag`, launch next
        anchor_after = jax.device_get(t.anchor)
        # leaves of the synced fragment moved, the others did not
        leaves_b, _ = jax.tree_util.tree_flatten(anchor_before)
        leaves_a, _ = jax.tree_util.tree_flatten(anchor_after)
        moved = [not np.allclose(x, y) for x, y in zip(leaves_b, leaves_a)]
        for i in range(len(moved)):
            assert moved[i] == (i in t._frag_idx[frag])

    def test_aborted_round_retries_same_fragment(self):
        fm = FakeManager()
        fm.commit_result = False
        t = make_streaming(fm, sync_every=4, fragments=2)
        target = jnp.full(4, 1.0)
        for _ in range(2):
            t.train_step(target)
        first_frag = t._pending[0]
        anchor_before = jax.device_get(t.anchor)
        _, committed = t.train_step(target) or (None, None)
        _, committed = t.train_step(target)
        assert committed is False
        np.testing.assert_allclose(
            jax.tree_util.tree_leaves(jax.device_get(t.anchor))[0],
            jax.tree_util.tree_leaves(anchor_before)[0])
        # the retry launches the SAME fragment (step did not bump)
        assert t._pending[0] == first_frag
        # recovery: next round commits and the anchor moves
        fm.commit_result = True
        for _ in range(2):
            t.train_step(target)
        assert t.flush() is True

    def test_fragment_split_balanced_nonempty(self):
        from torchft_tpu.local_sgd import _fragment_leaves
        leaves = [np.zeros(2), np.zeros(4)]
        assert _fragment_leaves(leaves, 2) == [[0], [1]]
        leaves = [np.zeros(100), np.zeros(1), np.zeros(1), np.zeros(1)]
        groups = _fragment_leaves(leaves, 3)
        assert [i for g in groups for i in g] == [0, 1, 2, 3]
        assert all(g for g in groups)
        assert _fragment_leaves([np.zeros(1)], 3) == [[0], [], []]


class TestDiLoCoUnit:
    def test_outer_round_cadence(self):
        manager = MagicMock()
        manager.should_commit.return_value = True
        manager.allreduce.side_effect = echo_allreduce
        t = make_trainer(manager, sync_every=4)
        target = jnp.full(4, 1.0)
        for i in range(4):
            _, committed = t.train_step(target)
            assert committed is (True if (i + 1) % 4 == 0 else None)
        assert manager.step.call_count == 1
        # right after the round, local params reset to the new anchor
        np.testing.assert_allclose(np.asarray(t.params["w"]),
                                   np.asarray(t.anchor["w"]))
        for i in range(3):
            _, committed = t.train_step(target)
            assert committed is None
        assert manager.step.call_count == 1  # still one outer round
        # inner steps moved local params off the anchor
        assert not np.allclose(np.asarray(t.params["w"]),
                               np.asarray(t.anchor["w"]))

    def test_inner_steps_do_not_communicate(self):
        manager = MagicMock()
        manager.allreduce.side_effect = echo_allreduce
        manager.should_commit.return_value = True
        t = make_trainer(manager, sync_every=100)
        for _ in range(50):
            t.train_step(jnp.ones(4))
        manager.step.assert_not_called()
        manager.allreduce.assert_not_called()

    def test_failed_round_keeps_local_progress(self):
        manager = MagicMock()
        manager.allreduce.side_effect = echo_allreduce
        manager.should_commit.return_value = False
        t = make_trainer(manager, sync_every=2)
        t.train_step(jnp.ones(4))
        params_before = np.asarray(t.params["w"])
        anchor_before = np.asarray(t.anchor["w"])
        _, committed = t.train_step(jnp.ones(4))
        assert committed is False
        # anchor untouched; local params kept training (≠ reset)
        np.testing.assert_allclose(np.asarray(t.anchor["w"]), anchor_before)
        assert not np.allclose(np.asarray(t.params["w"]), anchor_before)
        assert not np.allclose(np.asarray(t.params["w"]), params_before)

    def test_outer_applies_averaged_delta(self):
        manager = MagicMock()
        manager.should_commit.return_value = True
        # pretend the other group moved twice as far: average given back
        manager.allreduce.side_effect = echo_allreduce
        t = make_trainer(manager, sync_every=1)
        _, committed = t.train_step(jnp.full(4, 10.0))
        assert committed
        # outer sgd(0.7, nesterov 0.9): anchor moved toward params
        assert 0 < float(np.asarray(t.anchor["w"]).mean())


@pytest.mark.integration
class TestDiLoCoIntegration:
    def test_streaming_two_groups_anchors_identical(self):
        """Streaming DiLoCo: params drift locally by design, but every
        committed fragment round must land the same anchor on every group
        (the fragment schedule derives from the shared round counter)."""
        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=2,
                        join_timeout_ms=1000, quorum_tick_ms=50)

        def run_group(group):
            def loss_fn(params, batch):
                return jnp.mean((params["w"] - batch) ** 2
                                ) + jnp.mean((params["b"] - batch[:2]) ** 2)

            t = StreamingDiLoCoTrainer(
                loss_fn=loss_fn,
                inner_tx=optax.sgd(0.05),
                params={"w": jnp.zeros(4), "b": jnp.zeros(2)},
                manager_factory=lambda load, save: Manager(
                    comm=HostCommunicator(timeout_sec=15),
                    load_state_dict=load,
                    state_dict=save,
                    min_replica_size=2,
                    replica_id=f"sdiloco{group}",
                    lighthouse_addr=lh.address(),
                    rank=0, world_size=1,
                    timeout_ms=15_000, quorum_timeout_ms=15_000,
                ),
                sync_every=4,
                fragments=2,
            )
            target = jnp.full(4, float(group + 1))
            try:
                while t.manager.current_step() < 4:  # 4 fragment rounds
                    t.train_step(target)
                t.flush()
                return jax.device_get(t.anchor)
            finally:
                t.shutdown()

        try:
            with ThreadPoolExecutor(max_workers=2) as pool:
                futs = [pool.submit(run_group, g) for g in range(2)]
                results = [f.result(timeout=120) for f in futs]
        finally:
            lh.shutdown()
        np.testing.assert_array_equal(results[0]["w"], results[1]["w"])
        np.testing.assert_array_equal(results[0]["b"], results[1]["b"])
        assert float(np.abs(results[0]["w"]).mean()) > 0

    def test_streaming_death_and_heal_keeps_anchors_identical(self):
        """Kill+restart a group mid-stream: the rejoiner must pick the
        quorum-agreed fragment (not one derived from its stale local
        step), heal, and land bit-identical anchors. Guards the
        fragment-id-from-pre-quorum-step bug."""
        total = 6
        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1,
                        join_timeout_ms=500, quorum_tick_ms=20)

        def make(group):
            def loss_fn(params, batch):
                return (jnp.mean((params["w"] - batch) ** 2)
                        + jnp.mean((params["b"] - batch[:2]) ** 2))

            return StreamingDiLoCoTrainer(
                loss_fn=loss_fn,
                inner_tx=optax.sgd(0.05),
                params={"w": jnp.zeros(4), "b": jnp.zeros(2)},
                manager_factory=lambda load, save: Manager(
                    comm=HostCommunicator(timeout_sec=15),
                    load_state_dict=load,
                    state_dict=save,
                    min_replica_size=1,
                    replica_id=f"shl{group}",
                    lighthouse_addr=lh.address(),
                    rank=0, world_size=1,
                    timeout_ms=15_000, quorum_timeout_ms=15_000,
                ),
                sync_every=4,
                fragments=2,
            )

        def survivor():
            t = make(0)
            target = jnp.full(4, 1.0)
            try:
                while t.manager.current_step() < total:
                    t.train_step(target)
                t.flush()
                return jax.device_get(t.anchor)
            finally:
                t.shutdown()

        def victim():
            t = make(1)
            target = jnp.full(4, 2.0)
            try:
                while t.manager.current_step() < 2:
                    t.train_step(target)
            finally:
                t.shutdown()  # dies
            t = make(1)  # restart: fresh params, must rejoin + heal
            try:
                while t.manager.current_step() < total:
                    t.train_step(target)
                t.flush()
                assert t.manager.metrics()["heal_count"] >= 1
                return jax.device_get(t.anchor)
            finally:
                t.shutdown()

        try:
            with ThreadPoolExecutor(max_workers=2) as pool:
                fa, fb = pool.submit(survivor), pool.submit(victim)
                a, b_res = fa.result(timeout=180), fb.result(timeout=180)
        finally:
            lh.shutdown()
        np.testing.assert_array_equal(a["w"], b_res["w"])
        np.testing.assert_array_equal(a["b"], b_res["b"])

    def test_two_groups_converge_identically(self):
        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=2,
                        join_timeout_ms=1000, quorum_tick_ms=50)

        def run_group(group):
            def loss_fn(params, batch):
                return jnp.mean((params["w"] - batch) ** 2)

            t = DiLoCoTrainer(
                loss_fn=loss_fn,
                inner_tx=optax.sgd(0.05),
                params={"w": jnp.zeros(4)},
                manager_factory=lambda load, save: Manager(
                    comm=HostCommunicator(timeout_sec=15),
                    load_state_dict=load,
                    state_dict=save,
                    min_replica_size=2,
                    replica_id=f"diloco{group}",
                    lighthouse_addr=lh.address(),
                    rank=0, world_size=1,
                    timeout_ms=15_000, quorum_timeout_ms=15_000,
                ),
                sync_every=3,
            )
            # groups chase different targets; outer rounds reconcile
            target = jnp.full(4, float(group + 1))
            try:
                while t.manager.current_step() < 3:  # 3 outer rounds
                    t.train_step(target)
                return jax.device_get(t.anchor)
            finally:
                t.shutdown()

        try:
            with ThreadPoolExecutor(max_workers=2) as pool:
                futs = [pool.submit(run_group, g) for g in range(2)]
                results = [f.result(timeout=120) for f in futs]
        finally:
            lh.shutdown()
        np.testing.assert_array_equal(results[0]["w"], results[1]["w"])
        assert float(results[0]["w"].mean()) > 0  # moved off init
