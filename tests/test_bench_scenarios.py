"""Small-scale runs of the bench scenarios, asserting BASELINE.md's stated
recovery guarantees (<1 step of survivor progress lost per membership
change; healed group rejoins at the survivor's step, not from scratch)."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import pytest

import conftest  # noqa: E402
from bench import (bench_diloco, bench_long_context,  # noqa: E402
                   bench_multigroup, bench_recovery, bench_transformer)

# The multi-group scenarios need the native control plane (Lighthouse /
# Store); skip cleanly where no toolchain can build it.
requires_native = conftest.requires_native()


# Multi-group lighthouse/manager scenarios: integration tier.
pytestmark = pytest.mark.integration


class TestBenchScenarios:
    @requires_native
    def test_multigroup_traffic(self):
        out = bench_multigroup(n_groups=2, steps=3, hidden=32)
        assert out["steps_per_s"] > 0
        # Real cross-group traffic must have been measured.
        assert out["allreduce_ms_avg"] > 0
        assert out["grad_mbytes"] > 0
        # Stage attribution must be populated on the host path (the
        # fetch halves can measure ~0ms at this tiny size, but the ring
        # ran for real). Fetch is asserted through its dispatch/wait
        # split — the aggregate is just their sum and the split is what
        # makes a fetch-bound profile actionable.
        stages = out["stages_ms"]
        assert stages["ring"] > 0
        assert stages["fetch_dispatch"] >= 0
        assert stages["fetch_wait"] >= 0
        assert stages["fetch"] >= max(stages["fetch_dispatch"],
                                      stages["fetch_wait"])
        assert out["wire_mbytes_per_step"] > 0
        # Bytes crossed the TCP ring for real too (exact mode: same
        # payload both legs at 2 groups).
        assert out["ring_wire_mbytes_per_step"] > 0

    def test_rig_probes(self):
        from bench import bench_rig_probes
        out = bench_rig_probes(mbytes=0.5, reps=1)
        assert out["d2h_mb_s"] > 0
        assert out["h2d_mb_s"] > 0
        assert out["dispatch_ms"] > 0

    @requires_native
    def test_multigroup_mesh_backend(self):
        out = bench_multigroup(n_groups=2, steps=3, hidden=32,
                               backend="mesh")
        assert out["backend"] == "mesh"
        assert out["steps_per_s"] > 0
        assert out["allreduce_ms_avg"] > 0

    @requires_native
    def test_diloco_rate(self):
        out = bench_diloco(n_groups=2, sync_every=4, rounds=2, hidden=32)
        assert out["inner_steps_per_s"] > 0
        assert out["comm_per_step_frac"] == 0.25

    def test_transformer_smoke(self):
        out = bench_transformer()  # off-TPU: tiny smoke shape
        assert out["tokens_per_s"] > 0
        assert out["n_params"] > 0

    def test_long_context_smoke(self):
        out = bench_long_context()  # off-TPU: interpreter-mode smoke
        assert out["tokens_per_s"] > 0
        assert out["ms_per_fwd_bwd"] > 0

    @requires_native
    def test_recovery_guarantees(self):
        kill_at = 3
        out = bench_recovery(kill_at=kill_at, total_steps=12, hidden=16)
        # Survivor: at most one aborted step per membership change (the
        # victim leaving and rejoining = 2 changes), plus possibly its own
        # step-1 heal round.
        assert out["survivor_aborted_steps"] <= 3, out
        assert out["survivor_committed_steps"] >= 9, out
        # The restarted group healed to the survivor's current step instead
        # of replaying from scratch...
        assert out["victim_recovered_at_step"] > kill_at, out
        # ...and did so in bounded wall-clock.
        assert 0 < out["recovery_wall_clock_s"] < 60, out
        # The phase partition must actually partition: reinit + per-step
        # segments + other == total (round-4 verdict weak #3 demanded an
        # attribution with no dominant unattributed bucket). Bounds are
        # RELATIVE to the measured recovery wall clock (with a small
        # absolute floor for near-zero totals): absolute thresholds flaked
        # whenever a loaded CI core stretched the whole recovery, which
        # stretches every phase proportionally.
        total = out["recovery_wall_clock_s"]
        parts = (out["phase_reinit_s"] + out["phase_dispatch_compile_s"]
                 + out["phase_allreduce_wait_s"] + out["phase_commit_s"]
                 + out["phase_glue_s"] + out["phase_other_s"])
        assert abs(parts - total) < max(0.05, 0.02 * total), out
        # Loop overhead outside steps stays a small fraction of recovery.
        assert out["phase_other_s"] < max(0.3, 0.10 * total), out
