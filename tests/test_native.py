"""Tests for the ctypes bridge to the C++ control plane.

Python-side mirror of the reference's Rust inline tests
(/root/reference/src/lighthouse.rs:463-613, src/manager.rs:398-477), driven
through the same bindings the Manager runtime uses.
"""

import threading

import pytest

from torchft_tpu._native import (
    Lighthouse,
    ManagerClient,
    ManagerServer,
    NativeError,
    Store,
    StoreClient,
)


def test_store_set_get():
    server = Store(bind="127.0.0.1:0")
    try:
        a = StoreClient(server.address())
        b = StoreClient(server.address())
        a.set("key", b"value")
        assert b.get("key", timeout_ms=2000) == b"value"
        with pytest.raises(NativeError):
            b.get("missing", timeout_ms=50)
    finally:
        server.shutdown()


def test_store_blocking_get():
    server = Store(bind="127.0.0.1:0")
    try:
        a = StoreClient(server.address())
        b = StoreClient(server.address())
        t = threading.Timer(0.1, lambda: a.set("late", b"v"))
        t.start()
        assert b.get("late", timeout_ms=5000) == b"v"
        t.join()
    finally:
        server.shutdown()


def test_two_group_quorum_and_heal():
    lh = Lighthouse(bind="127.0.0.1:0", min_replicas=2, join_timeout_ms=100,
                    quorum_tick_ms=10)
    try:
        m_a = ManagerServer("group_a", lh.address(), store_addr="store_a",
                            bind="127.0.0.1:0", world_size=1)
        m_b = ManagerServer("group_b", lh.address(), store_addr="store_b",
                            bind="127.0.0.1:0", world_size=1)
        results = {}

        def run(name, server, step):
            client = ManagerClient(server.address())
            results[name] = client.quorum(
                rank=0, step=step, checkpoint_server_addr=f"ckpt_{name}",
                timeout_ms=10_000)

        # group_a is at step 5, group_b lags at step 3 → b heals from a.
        ta = threading.Thread(target=run, args=("a", m_a, 5))
        tb = threading.Thread(target=run, args=("b", m_b, 3))
        ta.start(); tb.start(); ta.join(); tb.join()

        ra, rb = results["a"], results["b"]
        assert ra.quorum_id == rb.quorum_id
        assert ra.max_step == rb.max_step == 5
        assert ra.replica_world_size == rb.replica_world_size == 2
        assert ra.replica_rank == 0 and rb.replica_rank == 1
        assert not ra.heal and rb.heal
        assert rb.recover_manager_address == m_a.address()
        assert ra.max_rank == 0 and rb.max_rank is None
        # Both groups rendezvous on participant[0]'s store.
        assert ra.store_address == rb.store_address == "store_a"

        # Healer fetches the primary's per-rank checkpoint address.
        healer = ManagerClient(ra.recover_manager_address)
        assert healer.checkpoint_address(0) == "ckpt_a"

        # Commit barrier: world_size=1 per group, immediate decision.
        ca = ManagerClient(m_a.address())
        assert ca.should_commit(0, 5, True, timeout_ms=5000)
        assert not ca.should_commit(0, 6, False, timeout_ms=5000)

        m_a.shutdown()
        m_b.shutdown()
    finally:
        lh.shutdown()


def test_local_rank_barrier():
    """All world_size local ranks must arrive before quorum returns."""
    lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=100,
                    quorum_tick_ms=10)
    try:
        m = ManagerServer("group", lh.address(), bind="127.0.0.1:0",
                          world_size=2)
        results = [None, None]

        def run(rank):
            client = ManagerClient(m.address())
            results[rank] = client.quorum(
                rank=rank, step=1, checkpoint_server_addr=f"ckpt_{rank}",
                timeout_ms=10_000)

        t0 = threading.Thread(target=run, args=(0,))
        t1 = threading.Thread(target=run, args=(1,))
        t0.start(); t1.start(); t0.join(); t1.join()
        assert results[0].quorum_id == results[1].quorum_id
        assert results[0].replica_world_size == 1

        # should_commit is an AND across local ranks.
        votes = [None, None]

        def vote(rank, ok):
            client = ManagerClient(m.address())
            votes[rank] = client.should_commit(rank, 1, ok, timeout_ms=10_000)

        t0 = threading.Thread(target=vote, args=(0, True))
        t1 = threading.Thread(target=vote, args=(1, False))
        t0.start(); t1.start(); t0.join(); t1.join()
        assert votes == [False, False]
        m.shutdown()
    finally:
        lh.shutdown()


def test_lighthouse_status():
    lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=50,
                    quorum_tick_ms=10)
    try:
        m = ManagerServer("solo", lh.address(), bind="127.0.0.1:0",
                          world_size=1)
        c = ManagerClient(m.address())
        c.quorum(rank=0, step=1, checkpoint_server_addr="x",
                 timeout_ms=10_000)
        status = lh.status()
        assert status["quorum_id"] >= 1
        assert [mm["replica_id"] for mm in status["members"]] == ["solo"]
        # GET /status.json serves the same document over plain HTTP (no
        # Python bridge needed — scrapers/SREs).
        import json
        import urllib.request

        req = urllib.request.urlopen(
            f"http://{lh.address()}/status.json", timeout=5)
        assert req.headers["Content-Type"] == "application/json"
        http_status = json.loads(req.read())
        assert http_status["quorum_id"] == status["quorum_id"]
        assert [mm["replica_id"] for mm in http_status["members"]] == ["solo"]
        m.shutdown()
    finally:
        lh.shutdown()


def test_heartbeat_grace_options_plumbed():
    """The straggler-grace knobs reach the C++ lighthouse (the grace
    semantics themselves are covered by core_test.cc); factor=1 restores
    reference behavior and must still form quorums."""
    lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=50,
                    quorum_tick_ms=10, heartbeat_fresh_ms=200,
                    heartbeat_grace_factor=1)
    try:
        m = ManagerServer("plumb", lh.address(), bind="127.0.0.1:0",
                          world_size=1)
        c = ManagerClient(m.address())
        q = c.quorum(rank=0, step=1, checkpoint_server_addr="x",
                     timeout_ms=10_000)
        assert q.replica_world_size == 1
        m.shutdown()
    finally:
        lh.shutdown()


def test_eviction_option_plumbed():
    """The fast-eviction knob reaches the C++ lighthouse (the eviction
    semantics are covered by core_test.cc); factor=0 disables it and must
    still form quorums."""
    for factor in (0, 3):
        lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1,
                        join_timeout_ms=50, quorum_tick_ms=10,
                        eviction_staleness_factor=factor)
        try:
            m = ManagerServer(f"evict{factor}", lh.address(),
                              bind="127.0.0.1:0", world_size=1)
            c = ManagerClient(m.address())
            q = c.quorum(rank=0, step=1, checkpoint_server_addr="x",
                         timeout_ms=10_000)
            assert q.replica_world_size == 1
            m.shutdown()
        finally:
            lh.shutdown()


def test_manager_metrics_endpoint():
    """VERDICT r3 missing #3: Manager.metrics() must be reachable from the
    outside. The Python Manager pushes metrics+history to its C++ server at
    each commit; the server serves them at GET /metrics.json on the RPC
    port, and the counters ride heartbeats onto the lighthouse status."""
    import json as _json
    import time as _time
    import urllib.request

    from torchft_tpu.communicator import DummyCommunicator
    from torchft_tpu.manager import Manager

    lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=100,
                    quorum_tick_ms=10)
    m = Manager(
        comm=DummyCommunicator(), load_state_dict=lambda s: None,
        state_dict=lambda: {}, min_replica_size=1, replica_id="metrics",
        lighthouse_addr=lh.address(), rank=0, world_size=1,
    )
    try:
        for _ in range(2):
            m.step()
            assert m.should_commit()
        addr = m._manager_server.address()
        got = _json.load(urllib.request.urlopen(
            f"http://{addr}/metrics.json", timeout=5))
        assert got["replica_id"].startswith("metrics:")
        st = got["status"]
        assert st["metrics"]["committed_steps"] == 2
        assert st["metrics"]["quorum_count"] >= 2
        assert isinstance(st["history"], list)
        assert any(e["event"] == "reconfigure" for e in st["history"])

        # The counters also ride heartbeats onto the lighthouse status.
        deadline = _time.time() + 5
        member = None
        while _time.time() < deadline:
            status = _json.load(urllib.request.urlopen(
                f"http://{lh.address()}/status.json", timeout=5))
            if status["members"] and \
                    status["members"][0].get("committed_steps") == 2:
                member = status["members"][0]
                break
            _time.sleep(0.1)
        assert member is not None, "lighthouse never saw pushed counters"
        assert member["heal_count"] == 0
        assert member["aborted_steps"] == 0
    finally:
        m.shutdown()
        lh.shutdown()


def test_step_retry_gets_fresh_rounds():
    """After a failed commit the Manager retries the SAME step; both the
    quorum and the vote must run fresh rounds, not replay the stale result
    (regression: step-keyed rounds livelocked retries forever)."""
    lh = Lighthouse(bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=50,
                    quorum_tick_ms=10)
    try:
        m = ManagerServer("retry", lh.address(), bind="127.0.0.1:0",
                          world_size=1)
        c = ManagerClient(m.address())

        q1 = c.quorum(rank=0, step=3, checkpoint_server_addr="a",
                      timeout_ms=10_000)
        assert c.should_commit(0, 3, False, timeout_ms=10_000) is False

        # Retry of step 3: a new vote round must be able to flip to True.
        q2 = c.quorum(rank=0, step=3, checkpoint_server_addr="a",
                      timeout_ms=10_000)
        assert q2.quorum_id >= q1.quorum_id
        assert c.should_commit(0, 3, True, timeout_ms=10_000) is True
        m.shutdown()
    finally:
        lh.shutdown()
