"""Flash attention + ring attention correctness vs the reference impl."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from torchft_tpu.models.transformer import plain_attention
from torchft_tpu.ops import flash_attention
from torchft_tpu.parallel import make_mesh
from torchft_tpu.parallel.ring_attention import make_ring_attention

# Compile-heavy tier: pallas interpret mode + sharded jit dominate suite
# wall-clock; scripts/test.sh runs these after the fast unit tier.
pytestmark = pytest.mark.heavy


def qkv(b=2, s=32, h=4, d=16, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        q, k, v = qkv()
        ref = plain_attention(q, k, v, causal)
        out = flash_attention(q, k, v, causal, 8, 8)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_single_block(self):
        q, k, v = qkv(s=16)
        ref = plain_attention(q, k, v, True)
        out = flash_attention(q, k, v, True, 16, 16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_causal_cross_shape_end_aligned(self):
        # s_q != s_k (decode-style): queries are the LAST s_q positions.
        # Forward and backward must use the same end-aligned mask
        # (round-1 ADVICE: the kernel was start-aligned, the vjp end-aligned).
        b, h, d = 2, 4, 16
        ks = jax.random.split(jax.random.key(3), 3)
        q = jax.random.normal(ks[0], (b, 8, h, d))
        k = jax.random.normal(ks[1], (b, 32, h, d))
        v = jax.random.normal(ks[2], (b, 32, h, d))
        ref = plain_attention(q, k, v, True)
        out = flash_attention(q, k, v, True, 8, 8)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
        gf = jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, True, 8, 8) ** 2), (0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(
            plain_attention(q, k, v, True) ** 2), (0, 1, 2))(q, k, v)
        for a, b_ in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=2e-4, rtol=2e-4)

    @pytest.mark.parametrize("s", [9, 100, 999])
    def test_odd_seq_lens_pad_exactly(self, s):
        """ADVICE r2: lengths with no sublane-aligned dividing tile are
        end-padded (q and k equally) instead of leaning on Mosaic's
        implicit padding; forward AND grads must match the reference
        bitwise-closely."""
        q, k, v = qkv(s=s)
        ref = plain_attention(q, k, v, True)
        out = flash_attention(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
        gf = jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, True) ** 2), (0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(
            plain_attention(q, k, v, True) ** 2), (0, 1, 2))(q, k, v)
        for a, b_ in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=2e-4, rtol=2e-4)

    def test_odd_seq_non_causal_raises(self):
        # ValueError (not assert): must survive `python -O`.
        q, k, v = qkv(s=999)
        with pytest.raises(ValueError, match="aligned"):
            flash_attention(q, k, v, False)

    def test_grads_match_reference(self):
        q, k, v = qkv(s=16)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, True, 8, 8) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(plain_attention(q, k, v, True) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-4)


class TestFlashAttentionGQA:
    """GQA/MQA kv heads are shared via kernel index maps — values and
    gradients must match the materialized-repeat path exactly."""

    def test_ring_attention_gqa(self):
        """Ring attention with GQA kv heads matches plain attention; the
        ring rotates the SMALL kv tensors."""
        from torchft_tpu.models.transformer import plain_attention
        from torchft_tpu.parallel import make_ring_attention
        from torchft_tpu.parallel.mesh import make_mesh

        mesh = make_mesh({"sp": 4}, devices=jax.devices()[:4])
        ring = make_ring_attention(mesh, axis="sp", batch_axes=())
        assert ring.supports_gqa
        q, _, _ = qkv(s=32, h=8)
        _, k, v = qkv(s=32, h=2, seed=5)
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(mesh, P(None, "sp", None, None))
        qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
        out = ring(qs, ks, vs, True)
        ref = plain_attention(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    @pytest.mark.parametrize("h_kv", [1, 2])
    def test_matches_repeat_path(self, h_kv):
        q, _, _ = qkv(s=32, h=8)
        _, k, v = qkv(s=32, h=h_kv, seed=3)
        rep = 8 // h_kv

        def loss_gqa(q, k, v):
            return jnp.sum(flash_attention(q, k, v, True, 8, 8) ** 2)

        def loss_rep(q, k, v):
            return jnp.sum(flash_attention(
                q, jnp.repeat(k, rep, 2), jnp.repeat(v, rep, 2),
                True, 8, 8) ** 2)

        np.testing.assert_allclose(float(loss_gqa(q, k, v)),
                                   float(loss_rep(q, k, v)), rtol=1e-5)
        g1 = jax.grad(loss_gqa, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_rep, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-5)


class TestFlashAttentionBlock:
    """The ring-attention building block: one flash pass against a K/V
    block with a TRACED mask shift, returning (out, lse) for
    online-softmax merging — differentiable through both outputs."""

    def test_shift_modes_match_reference(self):
        from torchft_tpu.ops.flash_attention import (_reference,
                                                     flash_attention_block)

        q, k, v = qkv(s=32)
        s = q.shape[1]
        out_f, _ = flash_attention_block(q, k, v, jnp.int32(s), 8, 8)
        np.testing.assert_allclose(
            np.asarray(out_f), np.asarray(_reference(q, k, v, False)),
            rtol=2e-5, atol=2e-5)
        out_c, _ = flash_attention_block(q, k, v, jnp.int32(0), 8, 8)
        np.testing.assert_allclose(
            np.asarray(out_c), np.asarray(_reference(q, k, v, True)),
            rtol=2e-5, atol=2e-5)
        # fully blocked: lse ~ -inf → zero weight when merged
        _, lse_b = flash_attention_block(q, k, v, jnp.int32(-s), 8, 8)
        assert float(jnp.max(lse_b)) < -1e29

    def test_merge_value_and_grads_match_dense(self):
        """Two blocks (one full, one diagonal-causal) merged via lse must
        equal dense attention over the concatenated keys — including
        gradients, which flow through the lse cotangent."""
        from torchft_tpu.ops.flash_attention import flash_attention_block

        q, k1, v1 = qkv(s=16)
        _, k2, v2 = qkv(s=16, seed=9)
        s = q.shape[1]
        b, _, h, _ = q.shape

        def per(w):
            return w.reshape(b, h, s).transpose(0, 2, 1)[..., None]

        def loss_merged(q, k1, v1, k2, v2):
            o1, l1 = flash_attention_block(q, k1, v1, jnp.int32(s), 8, 8)
            o2, l2 = flash_attention_block(q, k2, v2, jnp.int32(0), 8, 8)
            m = jnp.maximum(l1, l2)
            w1, w2 = jnp.exp(l1 - m), jnp.exp(l2 - m)
            out = (per(w1) * o1 + per(w2) * o2) / (per(w1) + per(w2))
            return jnp.sum(out ** 2)

        def loss_dense(q, k1, v1, k2, v2):
            kk = jnp.concatenate([k1, k2], axis=1)
            vv = jnp.concatenate([v1, v2], axis=1)
            scale = q.shape[-1] ** -0.5
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, kk) * scale
            qp = jnp.arange(s)[:, None]
            kp = jnp.arange(s)[None, :]
            mask = jnp.concatenate(
                [jnp.ones((s, s), bool), qp >= kp], axis=1)
            logits = jnp.where(mask[None, None], logits, -1e30)
            p = jax.nn.softmax(logits, axis=-1)
            return jnp.sum(jnp.einsum("bhqk,bkhd->bqhd", p, vv) ** 2)

        np.testing.assert_allclose(
            float(loss_merged(q, k1, v1, k2, v2)),
            float(loss_dense(q, k1, v1, k2, v2)), rtol=1e-4)
        gm = jax.grad(loss_merged, argnums=(0, 1, 2, 3, 4))(
            q, k1, v1, k2, v2)
        gd = jax.grad(loss_dense, argnums=(0, 1, 2, 3, 4))(
            q, k1, v1, k2, v2)
        for a, b_ in zip(gm, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=2e-4, atol=2e-4)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference_sp8(self, causal):
        mesh = make_mesh({"sp": 8})
        q, k, v = qkv(s=64)
        spec = NamedSharding(mesh, P(None, "sp", None, None))
        qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
        ring = make_ring_attention(mesh)
        out = jax.jit(lambda a, b, c: ring(a, b, c, causal))(qs, ks, vs)
        ref = plain_attention(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_mixed_dp_sp(self):
        mesh = make_mesh({"dp": 2, "sp": 4})
        q, k, v = qkv(b=4, s=32)
        spec = NamedSharding(mesh, P("dp", "sp", None, None))
        qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
        ring = make_ring_attention(mesh, batch_axes=("dp",))
        out = jax.jit(lambda a, b, c: ring(a, b, c, True))(qs, ks, vs)
        ref = plain_attention(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_differentiable(self):
        mesh = make_mesh({"sp": 8})
        q, k, v = qkv(s=32)
        ring = make_ring_attention(mesh)

        def loss_ring(q, k, v):
            return jnp.sum(ring(q, k, v, True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(plain_attention(q, k, v, True) ** 2)

        with mesh:
            gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        ge = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, ge):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-4)

    def test_sp1_falls_back(self):
        mesh = make_mesh({"dp": 8, "sp": 1})
        q, k, v = qkv()
        ring = make_ring_attention(mesh)
        out = ring(q, k, v, True)
        ref = plain_attention(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6)


class TestTransformerWithRing:
    def test_transformer_sp_forward_and_grad(self):
        from torchft_tpu.models import (
            Transformer, TransformerConfig, causal_lm_loss)

        mesh = make_mesh({"dp": 2, "sp": 4})
        ring = make_ring_attention(mesh, batch_axes=("dp",))
        kw = dict(vocab_size=128, num_layers=2, embed_dim=64, num_heads=4,
                  dtype=jnp.float32)
        cfg_ring = TransformerConfig(attention_fn=ring, **kw)
        cfg_ref = TransformerConfig(**kw)
        tokens = jax.random.randint(jax.random.key(1), (4, 32), 0, 128)
        params = Transformer(cfg_ref).init(jax.random.key(0), tokens)

        tok_sharded = jax.device_put(
            tokens, NamedSharding(mesh, P("dp", "sp")))
        with mesh:
            out_ring = jax.jit(
                lambda p, t: Transformer(cfg_ring).apply(p, t)
            )(params, tok_sharded)
        out_ref = Transformer(cfg_ref).apply(params, tokens)
        np.testing.assert_allclose(np.asarray(out_ring),
                                   np.asarray(out_ref),
                                   atol=2e-4, rtol=2e-4)

        with mesh:
            g_ring = jax.jit(jax.grad(
                lambda p, t: causal_lm_loss(
                    Transformer(cfg_ring).apply(p, t), t)
            ))(params, tok_sharded)
        g_ref = jax.grad(
            lambda p, t: causal_lm_loss(Transformer(cfg_ref).apply(p, t), t)
        )(params, tokens)
        flat_r = jax.tree_util.tree_leaves(g_ring)
        flat_e = jax.tree_util.tree_leaves(g_ref)
        for a, b in zip(flat_r, flat_e):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=3e-4, rtol=3e-3)


@pytest.mark.nightly
@pytest.mark.slow
class TestFusedBwdHardware:
    """Recurring real-device validation of the fused-bwd dq RMW (the
    nqb>=4 gate is empirical; interpret mode can't catch a Mosaic
    pipelining race — see flash_attention.py's safety contract).

    Marked slow as well as nightly: the subprocess probes for a REAL
    TPU with JAX_PLATFORMS unset, and on a TPU-less box the plugin's
    driver-connect retries burn minutes of wall clock before the check
    exits 75 (skip) — that probe must never sit in the per-commit
    tier-1 budget (this rides `scripts/test.sh nightly`, -m "nightly
    or slow", as the module docstring promises)."""

    def test_fused_matches_split_on_hardware(self):
        import subprocess
        import sys as _sys

        env = dict(os.environ)
        # Undo the suite's forced-CPU config so the subprocess can see a
        # real TPU if one is attached.
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
        r = subprocess.run(
            [_sys.executable, "-m", "torchft_tpu.ops.fused_bwd_check"],
            env=env, capture_output=True, text=True, timeout=600)
        if r.returncode == 75:
            pytest.skip("no TPU attached: " + r.stderr.strip())
        assert r.returncode == 0, (
            f"fused-vs-split hardware mismatch:\n{r.stdout}\n{r.stderr}")
