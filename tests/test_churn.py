"""Spot-instance churn tests (ISSUE 14, docs/design/churn.md).

Tier-1 (marker ``churn``, ``scripts/test.sh churn``): the seeded
:class:`~torchft_tpu.chaos.ChurnOrchestrator` event stream, the
Manager's graceful-preemption drain state machine (notice → clean
commit boundary → farewell → final durable save → advertisement
withdrawal → :class:`~torchft_tpu.manager.PreemptedExit`; deferral
mid-heal / mid-deferred / errored / aborted; deadline expiry with a
flight dump), the SIGTERM handler, manager-side join-coalescing and
reconfigures-per-minute accounting, the pre-join heal (join
backpressure over the REAL checkpoint HTTP transport), chaos
kill-latch rebirth for address-reusing replacements, and the 2-group
graceful-vs-SIGKILL A/B drive over a real socketpair ring (the
acceptance oracle: the graceful leg's survivor commits every step with
zero vote aborts and zero ring-reset latches; the SIGKILL control leg
shows at least one abort).

The lighthouse-side join-coalescing window and the farewell-races-
fast-path regression run in the C++ core tier (core_test.cc); the
Poisson churn soak (``bench_churn_goodput`` gates: >= 0.8x zero-churn
goodput at graceful churn, bitwise convergence through membership
drift) is native-gated and rides nightly.
"""

import os
import signal
import threading
import time
from unittest.mock import MagicMock

import numpy as np
import pytest

import conftest
from torchft_tpu import chaos
from torchft_tpu._native import QuorumResult
from torchft_tpu.chaos import ChaosSchedule, ChurnOrchestrator, EndpointChaos
from torchft_tpu.checkpointing import CheckpointServer
from torchft_tpu.communicator import DummyCommunicator
from torchft_tpu.manager import Manager, PreemptedExit

requires_native = conftest.requires_native()

pytestmark = pytest.mark.churn


def quorum_result(
    quorum_id=1,
    recover_manager_address="manager:1234",
    store_address="s:1",
    max_step=1,
    max_rank=0,
    max_world_size=2,
    replica_rank=0,
    replica_world_size=2,
    heal=False,
):
    return QuorumResult(
        quorum_id=quorum_id,
        recover_manager_address=recover_manager_address,
        store_address=store_address,
        max_step=max_step,
        max_rank=max_rank,
        max_world_size=max_world_size,
        replica_rank=replica_rank,
        replica_world_size=replica_world_size,
        heal=heal,
    )


def make_manager(client, comm=None, min_replica_size=1, **kwargs):
    return Manager(
        comm=comm or DummyCommunicator(),
        load_state_dict=kwargs.pop("load_state_dict", MagicMock()),
        state_dict=kwargs.pop("state_dict",
                              lambda: {"w": np.ones(4, np.float32)}),
        min_replica_size=min_replica_size,
        rank=0,
        world_size=1,
        replica_id=kwargs.pop("replica_id", "churntest"),
        _manager_client=client,
        **kwargs,
    )


def boundary(m, tree=None):
    m.step()
    m.allreduce(tree if tree is not None
                else {"g": np.ones(4, np.float32)}).result()
    return m.should_commit()


class FakeStore:
    """Dict-backed stand-in for the native StoreClient, injectable via
    the Manager's per-address store-client cache."""

    def __init__(self):
        self.kv = {}
        self.lock = threading.Lock()

    def set(self, key, value):
        with self.lock:
            self.kv[key] = value if isinstance(value, bytes) \
                else str(value).encode()

    def get(self, key, timeout_ms=0):
        with self.lock:
            if key not in self.kv:
                raise KeyError(key)
            return self.kv[key]


# ------------------------------------------------------ ChurnOrchestrator


class TestChurnOrchestrator:
    def _drive(self, o, seconds, dt=0.5):
        acts = []
        t = 0.0
        while t <= seconds:
            acts += o.tick(t)
            t += dt
        return acts

    def test_same_seed_same_event_stream(self):
        mk = lambda: ChurnOrchestrator(  # noqa: E731
            seed=7, groups=["a", "b", "c", "d"], rate_per_min=20,
            graceful_frac=0.5, replace_delay_s=1.0)
        a, b = mk(), mk()
        assert self._drive(a, 300) == self._drive(b, 300)
        assert a.notices == b.notices and a.kills == b.kills
        assert a.notices > 0 and a.kills > 0

    def test_different_seed_different_stream(self):
        a = ChurnOrchestrator(seed=1, groups=["a", "b"], rate_per_min=30)
        b = ChurnOrchestrator(seed=2, groups=["a", "b"], rate_per_min=30)
        assert self._drive(a, 300) != self._drive(b, 300)

    def test_zero_rate_is_silent(self):
        o = ChurnOrchestrator(seed=1, groups=["a", "b"], rate_per_min=0.0)
        assert self._drive(o, 600) == []
        assert o.notices == o.kills == 0

    def test_rate_scales_event_count(self):
        slow = ChurnOrchestrator(seed=3, groups=list(range(8)),
                                 rate_per_min=6, replace_delay_s=0.0)
        fast = ChurnOrchestrator(seed=3, groups=list(range(8)),
                                 rate_per_min=60, replace_delay_s=0.0)
        self._drive(slow, 600)
        self._drive(fast, 600)
        assert fast.notices + fast.kills > 3 * (slow.notices + slow.kills)

    def test_graceful_frac_extremes(self):
        g = ChurnOrchestrator(seed=5, groups=["a", "b", "c"],
                              rate_per_min=30, graceful_frac=1.0)
        k = ChurnOrchestrator(seed=5, groups=["a", "b", "c"],
                              rate_per_min=30, graceful_frac=0.0)
        self._drive(g, 300)
        self._drive(k, 300)
        assert g.kills == 0 and g.notices > 0
        assert k.notices == 0 and k.kills > 0
        # Same seed, same victims/times: only the notice/kill flavor
        # differs — the A/B legs of the bench see the identical storm.
        assert [(t, gid) for t, _, gid in g.events] \
            == [(t, gid) for t, _, gid in k.events]

    def test_min_live_floor_holds(self):
        fired = []
        o = ChurnOrchestrator(seed=9, groups=["a", "b"], rate_per_min=120,
                              graceful_frac=0.0,
                              kill=fired.append,
                              replace_delay_s=-1.0,  # never respawn
                              min_live=1)
        self._drive(o, 600)
        assert len(o.live) == 1
        assert len(fired) == 1  # one kill allowed, then the floor holds
        assert o.skipped_min_live > 0

    def test_replacement_scheduling_and_callback(self):
        replaced = []
        o = ChurnOrchestrator(seed=11, groups=["a", "b", "c"],
                              rate_per_min=60, graceful_frac=0.0,
                              replace=replaced.append,
                              replace_delay_s=5.0, min_live=1)
        acts = self._drive(o, 120)
        kills = [a for a in acts if a[1] == "kill"]
        repl = [a for a in acts if a[1] == "replace"]
        assert kills and repl
        assert o.replacements == len(replaced) == len(repl)
        # Every replacement respawned >= replace_delay_s after its kill.
        kill_t = {}
        for t, kind, gid in acts:
            if kind == "kill":
                kill_t[gid] = t
            elif kind == "replace":
                assert t - kill_t[gid] >= 5.0

    def test_set_rate_moves_intensity_live(self):
        o = ChurnOrchestrator(seed=13, groups=list(range(4)),
                              rate_per_min=0.0, replace_delay_s=0.0)
        assert self._drive(o, 300) == []
        o.set_rate(60.0)
        assert len(self._drive(o, 300)) > 0


# --------------------------------------------------- drain state machine


class TestPreemptionDrain:
    def participant_client(self, **kw):
        client = MagicMock()
        client.quorum.return_value = quorum_result(**kw)
        client.should_commit.return_value = True
        return client

    def test_happy_path_drain_sequence(self, tmp_path):
        from torchft_tpu import checkpoint_io
        from torchft_tpu.checkpoint_io import AsyncCheckpointer

        client = self.participant_client(replica_rank=1, max_rank=1)
        store = FakeStore()
        m = make_manager(client)
        m._healset_store = ("s:1", store)  # inject the quorum store
        writer = AsyncCheckpointer()
        m.set_durable_target(writer, str(tmp_path))
        pub = MagicMock()
        m._publisher = pub

        assert boundary(m)
        # Healset advertised (rank 1, step "1:<addr>" prefix).
        assert store.kv["torchft/healset/1"].startswith(b"1:")

        remaining = m.request_preemption(60.0, reason="reclaim-test")
        assert 0 < remaining <= 60.0
        assert m.preemption_pending()
        assert not m.drained()

        # The last boundary was clean: the drain lands at the next
        # step() — its post-apply edge, where the caller has applied
        # the committed update — and that same call raises.
        with pytest.raises(PreemptedExit):
            m.step()
        assert m.drained()
        assert not m.preemption_pending()
        # (1) farewell went out via the duck-typed client hook.
        assert client.farewell.called
        # (2) final durable save landed at the drained step.
        rec = checkpoint_io.recover(str(tmp_path))
        assert rec is not None
        _user, mgr_state = checkpoint_io.load(
            rec, target={"w": np.ones(4, np.float32)})
        assert mgr_state["step"] == 1  # the committed boundary's step
        # (3) healset advertisement tombstoned (step -1 never matches a
        # heal's max_step, so _healset_donors filters it out).
        assert store.kv["torchft/healset/1"] == b"-1:"
        mx = m.metrics()
        assert mx["preempt_notices_total"] == 1
        assert mx["graceful_exits_total"] == 1
        assert mx["preempt_deadline_expired_total"] == 0
        events = [e["event"] for e in m.history()]
        assert "preempt_notice" in events
        assert "farewell" in events
        assert "graceful_exit" in events
        # (4) the loop stays out: every later step() refuses too.
        with pytest.raises(PreemptedExit):
            m.step()

    def test_drain_without_durable_target_still_exits(self):
        client = self.participant_client()
        m = make_manager(client)
        assert boundary(m)
        m.request_preemption(60.0)
        with pytest.raises(PreemptedExit):
            m.step()
        assert m.drained()
        assert m.metrics()["graceful_exits_total"] == 1

    def test_tombstoned_healset_entry_is_filtered_from_donor_sets(self):
        client = self.participant_client()
        store = FakeStore()
        store.set("torchft/healset/1", b"-1:")
        store.set("torchft/healset/2", b"3:http://live:1/checkpoint/3")
        m = make_manager(client)
        m._healset_store = ("s:1", store)
        q = quorum_result(max_step=3, max_world_size=3, replica_rank=0)
        donors = m._healset_donors(q, "http://primary:1/checkpoint/3")
        assert donors == ["http://primary:1/checkpoint/3",
                          "http://live:1/checkpoint/3"]
        m.shutdown()

    def test_vote_abort_defers_drain_to_next_boundary(self):
        client = self.participant_client()
        client.should_commit.side_effect = [False, True]
        m = make_manager(client)
        try:
            assert not boundary(m)  # aborted boundary
            m.request_preemption(60.0)
            # The next step sees an aborted last boundary: drain defers
            # and the step RETRIES normally.
            assert boundary(m)
            assert not m.drained()
            assert m.preemption_pending()
            mx = m.metrics()
            assert mx["preempt_drain_deferrals_total"] == 1
            evs = [e for e in m.history() if e["event"] == "preempt_deferred"]
            assert evs and "vote aborted" in evs[0]["why"]
            with pytest.raises(PreemptedExit):
                m.step()  # clean boundary behind us: drain lands
            assert m.drained()
        finally:
            if not m.drained():
                m.shutdown()

    def test_errored_boundary_defers_drain(self):
        client = self.participant_client()
        client.should_commit.side_effect = \
            lambda rank, step, should_commit, timeout_ms=None: should_commit
        m = make_manager(client)
        try:
            m.step()
            m.report_error(RuntimeError("injected"))
            assert not m.should_commit()
            m.request_preemption(60.0)
            # Next step: the latched error (and aborted vote) defer the
            # drain; the step itself retries normally and commits.
            assert boundary(m)
            assert not m.drained()
            assert m.metrics()["preempt_drain_deferrals_total"] == 1
            evs = [e for e in m.history() if e["event"] == "preempt_deferred"]
            assert "errored" in evs[0]["why"]
            with pytest.raises(PreemptedExit):
                m.step()
            assert m.drained()
        finally:
            if not m.drained():
                m.shutdown()

    def test_sigterm_mid_heal_defers_cleanly(self):
        """SIGTERM satellite: a notice landing while a heal is staged
        must defer the drain — a final save then would persist the
        inconsistent mid-heal state — and land cleanly at the next
        boundary once the heal settled."""
        client = self.participant_client()
        m = make_manager(client)
        try:
            assert boundary(m)
            # Simulate the quorum thread having marked a heal in flight
            # (the staged-restore window save_durable also refuses in).
            with m._metrics_lock:
                m._healing = True
            m.request_preemption(60.0)
            # The notice lands mid-heal: the drain defers and the step
            # proceeds normally (step() clears the heal flag itself as
            # the heal settles).
            assert boundary(m)
            assert not m.drained()
            mx = m.metrics()
            assert mx["preempt_drain_deferrals_total"] == 1
            evs = [e for e in m.history() if e["event"] == "preempt_deferred"]
            assert "healing" in evs[0]["why"]
            # Heal settled + clean boundary behind us: the drain lands.
            with pytest.raises(PreemptedExit):
                m.step()
            assert m.drained()
        finally:
            if not m.drained():
                m.shutdown()

    def test_sigterm_mid_deferred_overlap_defers_cleanly(self):
        """SIGTERM satellite: with a deferred allreduce still in flight
        (overlap mode), the boundary must NOT tear the drain through it
        — the deferral waits for the settle, then the next boundary
        drains."""
        client = self.participant_client()
        m = make_manager(client, overlap_steps=1)
        try:
            m.step()
            fut = m.allreduce({"g": np.ones(4, np.float32)})
            m.stage_deferred(fut)
            m.request_preemption(60.0)
            # Nothing may tear the staged step: a premature step() is
            # refused by the overlap guard AND the drain defers first
            # (never fires through an in-flight deferred commit).
            with pytest.raises(RuntimeError, match="deferred"):
                m.step()
            assert not m.drained()
            assert m.deferred_pending()
            evs = [e for e in m.history() if e["event"] == "preempt_deferred"]
            assert evs and "deferred in flight" in evs[0]["why"]
            # The settle (DelayedOptimizer's job) clears the staged
            # step; the drain then lands at the post-apply edge.
            assert m.drain_deferred() is not None
            assert m.should_commit()
            assert not m.drained()
            with pytest.raises(PreemptedExit):
                m.step()
            assert m.drained()
        finally:
            if not m.drained():
                m.shutdown()

    def test_deadline_expiry_degrades_to_hard_kill_with_flight_dump(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("TORCHFT_FLIGHT_DIR", str(tmp_path))
        client = self.participant_client()
        client.should_commit.return_value = False  # forever blocked
        m = make_manager(client)
        try:
            assert not boundary(m)  # vote aborted
            m.request_preemption(0.2)
            assert not boundary(m)  # blocked, inside deadline: deferred
            assert m.preemption_pending()
            time.sleep(0.25)
            assert not boundary(m)  # past deadline: expire, not drain
            assert not m.drained()
            assert not m.preemption_pending()  # expired = no longer armed
            mx = m.metrics()
            assert mx["preempt_deadline_expired_total"] == 1
            assert mx["graceful_exits_total"] == 0
            assert [e for e in m.history()
                    if e["event"] == "preempt_deadline_expired"]
            # The flight recorder dumped the postmortem.
            assert mx["flight_dumps_total"] >= 1
            assert any(f.endswith(".json") for f in os.listdir(tmp_path))
            # Later boundaries are undisturbed (hard-kill behavior:
            # keep running until the SIGKILL lands).
            client.should_commit.return_value = True
            assert boundary(m)
            assert not m.drained()
        finally:
            m.shutdown()

    def test_save_durable_with_user_state_is_not_auto_remembered(self, tmp_path):
        """A cadence save passing an explicit user_state must NOT arm
        the drain's auto-remembered target: the drain would write the
        manager-registered tree while every cadence file holds the
        caller's richer one — the newest checkpoint would then break
        cold-start resume on the structure mismatch. Such callers
        register via set_durable_target(user_state_fn=...)."""
        from torchft_tpu.checkpoint_io import AsyncCheckpointer

        client = self.participant_client()
        m = make_manager(client)
        try:
            writer = AsyncCheckpointer()
            assert boundary(m)
            fut = m.save_durable(writer, str(tmp_path),
                                 user_state={"rich": {"w": np.ones(2)}})
            assert fut is not None
            fut.result(timeout=30)
            assert m._durable_target is None  # no mismatched drain save
            # A plain save (manager-registered tree) IS remembered.
            fut = m.save_durable(writer, str(tmp_path))
            fut.result(timeout=30)
            assert m._durable_target is not None
        finally:
            m.shutdown()

    def test_fresh_notice_rearms_after_expiry(self):
        """Spot reprieve then re-reclaim: a notice arriving AFTER an
        earlier notice expired must re-arm the drain with the NEW
        deadline (not min() against the long-dead one, which would
        leave the drain inert forever)."""
        client = self.participant_client()
        client.should_commit.return_value = False
        m = make_manager(client)
        try:
            assert not boundary(m)
            m.request_preemption(0.2)
            assert not boundary(m)  # deferred (vote aborted)
            time.sleep(0.25)
            assert not boundary(m)  # expired
            assert not m.preemption_pending()
            # The reclaim was cancelled; a fresh one arrives later.
            remaining = m.request_preemption(60.0, reason="re-reclaim")
            assert remaining > 50.0  # re-armed, not a negative stale min
            assert m.preemption_pending()
            client.should_commit.return_value = True
            assert boundary(m)  # deferred once more (last vote aborted)
            with pytest.raises(PreemptedExit):
                m.step()
            assert m.drained()
            mx = m.metrics()
            assert mx["preempt_deadline_expired_total"] == 1
            assert mx["graceful_exits_total"] == 1
        finally:
            if not m.drained():
                m.shutdown()

    def test_refused_final_save_degrades_instead_of_lying(self):
        """A final save that save_durable REFUSES (state turned unclean
        between the drain's check and the save) must degrade to the
        hard-kill path — never complete the drain claiming a final
        save that was not written."""
        client = self.participant_client()
        m = make_manager(client)
        try:
            m.set_durable_target(MagicMock(), "/nonexistent")
            assert boundary(m)
            m.request_preemption(60.0)
            m.save_durable = MagicMock(return_value=None)  # refusal
            m.step()  # drain attempt: save refused -> degrade, no raise
            assert not m.drained()
            mx = m.metrics()
            assert mx["preempt_deadline_expired_total"] == 1
            assert mx["graceful_exits_total"] == 0
            assert any("refused" in str(e.get("why", ""))
                       for e in m.history()
                       if e["event"] == "preempt_deadline_expired")
        finally:
            m.shutdown()

    def test_repeated_notices_count_and_keep_earliest_deadline(self):
        client = self.participant_client()
        m = make_manager(client)
        try:
            m.request_preemption(120.0)
            remaining = m.request_preemption(60.0)
            assert remaining <= 60.0
            # A later, LONGER notice must not extend the armed deadline.
            remaining = m.request_preemption(300.0)
            assert remaining <= 60.0
            assert m.metrics()["preempt_notices_total"] == 3
        finally:
            m.shutdown()

    def test_reclaim_sec_env_default(self, monkeypatch):
        monkeypatch.setenv("TORCHFT_RECLAIM_SEC", "42")
        client = self.participant_client()
        m = make_manager(client)
        try:
            assert m.request_preemption() == pytest.approx(42.0, abs=1.0)
        finally:
            m.shutdown()

    def test_sigterm_handler_requests_preemption(self):
        client = self.participant_client()
        m = make_manager(client)
        prev = None
        try:
            prev = m.install_preemption_handler(deadline_s=30.0)
            os.kill(os.getpid(), signal.SIGTERM)
            # Python delivers the signal on the main thread at the next
            # bytecode boundary; give it one.
            for _ in range(100):
                if m.preemption_pending():
                    break
                time.sleep(0.01)
            assert m.preemption_pending()
            # The handler is lock-free (a signal can interrupt a frame
            # HOLDING _metrics_lock — taking it again would deadlock
            # the drain): the counter lands at the next boundary's
            # flush, not inside the handler.
            assert m.metrics()["preempt_notices_total"] == 0
            with pytest.raises(PreemptedExit):
                m.step()  # clean init boundary: flush + drain
            assert m.metrics()["preempt_notices_total"] == 1
        finally:
            if prev is not None:
                signal.signal(signal.SIGTERM, prev)
            m.shutdown()

    def test_publication_detaches_on_drain(self):
        from torchft_tpu.serving import WeightPublisher

        client = self.participant_client()
        m = make_manager(client)
        pub = WeightPublisher()
        assert boundary(m)
        assert m.publish(pub) is not None
        assert m._ckpt_server._publication is pub
        m.request_preemption(60.0)
        with pytest.raises(PreemptedExit):
            m.step()
        assert m.drained()
        # Withdrawn: the next /publish head poll 404s and subscribers
        # rotate away (checkpointing.detach_publication).
        assert m._ckpt_server._publication is None


# ------------------------------------- join/churn accounting (manager)


class TestJoinChurnAccounting:
    def test_joins_coalesced_counts_multi_member_growth(self):
        client = MagicMock()
        client.quorum.side_effect = [
            quorum_result(quorum_id=1, replica_world_size=2),
            # One reconfigure admits THREE joiners at once (world 2->5):
            # two of them rode an already-open coalescing window.
            quorum_result(quorum_id=2, replica_world_size=5,
                          max_world_size=5),
            # Shrink: never counted.
            quorum_result(quorum_id=3, replica_world_size=3,
                          max_world_size=3),
            # Single joiner: nothing coalesced.
            quorum_result(quorum_id=4, replica_world_size=4,
                          max_world_size=4),
        ]
        client.should_commit.return_value = True
        m = make_manager(client)
        try:
            for _ in range(4):
                assert boundary(m)
            mx = m.metrics()
            assert mx["joins_coalesced_total"] == 2
            assert mx["reconfigure_count"] == 4
            assert mx["reconfigures_per_min"] == 4.0
        finally:
            m.shutdown()

    def test_own_first_join_is_not_coalescing(self):
        client = MagicMock()
        # Our first round lands in a 5-group fleet: the world "jump"
        # from 0 is just us discovering it, not a coalesced admission.
        client.quorum.return_value = quorum_result(
            quorum_id=9, replica_world_size=5, max_world_size=5)
        client.should_commit.return_value = True
        m = make_manager(client)
        try:
            assert boundary(m)
            assert m.metrics()["joins_coalesced_total"] == 0
        finally:
            m.shutdown()

    def test_churn_rate_feeds_policy_signals(self):
        from torchft_tpu.policy import PolicyController

        c = PolicyController(window=4, escalate_failures=2,
                             relax_after=3, cooldown=1)
        c.note_boundary(True, churn_rate=7.0)
        assert c.last_signals.churn_rate == 7.0
        assert c.last_signals.as_dict()["churn_rate"] == 7.0


# ----------------------------------------- pre-join heal (backpressure)


class TestPrejoinHeal:
    def _fleet_state(self):
        return {
            "user": {"w": np.arange(8, dtype=np.float32) * 3.0},
            "torchft": {"step": 7, "batches_committed": 21},
        }

    def test_prejoin_adopts_fleet_state_over_real_http(self):
        donor_state = self._fleet_state()
        srv = CheckpointServer(lambda: donor_state)
        srv.allow_checkpoint(7)
        holder = {}
        client = MagicMock()
        m = make_manager(client,
                         load_state_dict=lambda s: holder.update(p=s),
                         state_dict=lambda: {"w": np.zeros(8, np.float32)})
        try:
            status = {"members": [
                {"replica_id": "donor", "address": "mgr:1", "step": 7},
            ]}
            ok = m.prejoin_heal(lambda: status,
                                resolve=lambda addr: srv.address())
            assert ok is True
            assert m.current_step() == 7
            assert m.batches_committed() == 21
            got = np.asarray(holder["p"]["w"])
            assert got.tobytes() == donor_state["user"]["w"].tobytes()
            mx = m.metrics()
            assert mx["prejoin_heals_total"] == 1
            assert mx["heal_bytes_total"] > 0
            assert [e for e in m.history() if e["event"] == "prejoin_heal"]
        finally:
            m.shutdown()
            srv.shutdown()

    def test_prejoin_stripes_across_max_step_members(self):
        donor_state = self._fleet_state()
        srvs = [CheckpointServer(lambda: donor_state) for _ in range(2)]
        for s in srvs:
            s.allow_checkpoint(7)
        holder = {}
        m = make_manager(MagicMock(),
                         load_state_dict=lambda s: holder.update(p=s),
                         state_dict=lambda: {"w": np.zeros(8, np.float32)})
        try:
            status = {"members": [
                {"replica_id": "d0", "address": "m0:1", "step": 7},
                {"replica_id": "d1", "address": "m1:1", "step": 7},
                {"replica_id": "lag", "address": "m2:1", "step": 5},
            ]}
            addrs = {"m0:1": srvs[0].address(), "m1:1": srvs[1].address()}
            ok = m.prejoin_heal(lambda: status,
                                resolve=lambda addr: addrs[addr])
            assert ok is True
            assert m.current_step() == 7
            got = np.asarray(holder["p"]["w"])
            assert got.tobytes() == donor_state["user"]["w"].tobytes()
        finally:
            m.shutdown()
            for s in srvs:
                s.shutdown()

    def test_prejoin_noop_when_already_current_or_no_fleet(self):
        m = make_manager(MagicMock())
        try:
            assert m.prejoin_heal(lambda: {"members": []}) is False
            # Fleet at our step: nothing to adopt.
            assert m.prejoin_heal(lambda: {"members": [
                {"replica_id": "d", "address": "m:1", "step": 0}]}) is False
            assert m.metrics()["prejoin_heals_total"] == 0
        finally:
            m.shutdown()

    def test_prejoin_failure_is_best_effort(self):
        m = make_manager(MagicMock())
        try:
            status = {"members": [
                {"replica_id": "d", "address": "m:1", "step": 9}]}

            def bad_resolve(addr):
                raise ConnectionRefusedError("donor gone")

            assert m.prejoin_heal(lambda: status,
                                  resolve=bad_resolve) is False
            assert m.current_step() == 0  # untouched; in-quorum heal covers
        finally:
            m.shutdown()

    def test_prejoin_refused_after_first_quorum_join(self):
        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = True
        m = make_manager(client)
        try:
            assert boundary(m)
            with pytest.raises(RuntimeError, match="BEFORE the first"):
                m.prejoin_heal(lambda: {"members": []})
        finally:
            m.shutdown()


# ------------------------------------------------- kill-latch rebirth


class TestKillLatchRebirth:
    def test_endpoint_reborn_clears_latch_and_byte_account(self):
        sched = ChaosSchedule(seed=1, endpoints={
            "heal": EndpointChaos(kill_after_bytes=100)})
        chaos.install(sched)
        try:
            sched.kill_endpoint("heal:h:1")
            sched.note_bytes("heal:h:1", 100)
            assert sched.is_dead("heal:h:1")
            chaos.endpoint_reborn("heal:h:1", "serve:h:1")
            assert not sched.is_dead("heal:h:1")
            # The byte account reset with the latch: the replacement
            # gets the full kill_after_bytes allowance, not instant
            # re-death on its first byte.
            assert sched.kill_allowance("heal:h:1") == 100
        finally:
            chaos.uninstall()

    def test_endpoint_reborn_noop_without_schedule(self):
        chaos.uninstall()
        chaos.endpoint_reborn("heal:x:1")  # must not raise

    def test_replacement_checkpoint_server_revives_inherited_latch(self):
        """The soak-blocking bug: a replacement binding a dead member's
        host:port inherited the corpse's kill latch — every dial
        refused forever. A fresh server at the address must revive it."""
        sched = ChaosSchedule(seed=1, endpoints={})
        chaos.install(sched)
        try:
            state = {"w": np.ones(4, np.float32)}
            first = CheckpointServer(lambda: state, bind_host="127.0.0.1")
            import urllib.parse

            netloc = urllib.parse.urlparse(first.address()).netloc
            port = int(netloc.rsplit(":", 1)[1])
            # The member dies; chaos latches its endpoints dead.
            first.shutdown()
            sched.kill_endpoint(f"heal:{netloc}")
            sched.kill_endpoint(f"serve:{netloc}")
            # The replacement reuses the address: bind revives both.
            second = CheckpointServer(lambda: state,
                                      bind_host="127.0.0.1",
                                      bind_port=port)
            try:
                assert not sched.is_dead(f"heal:{netloc}")
                assert not sched.is_dead(f"serve:{netloc}")
            finally:
                second.shutdown()
        finally:
            chaos.uninstall()

    def test_replacement_publication_server_revives_latch(self):
        from torchft_tpu.serving import PublicationServer, WeightPublisher

        sched = ChaosSchedule(seed=1, endpoints={})
        chaos.install(sched)
        try:
            pub = WeightPublisher()
            first = PublicationServer(pub, bind_host="127.0.0.1")
            import urllib.parse

            netloc = urllib.parse.urlparse(first.address()).netloc
            port = int(netloc.rsplit(":", 1)[1])
            first.shutdown()
            sched.kill_endpoint(f"serve:{netloc}")
            second = PublicationServer(pub, bind_host="127.0.0.1",
                                       port=port)
            try:
                assert not sched.is_dead(f"serve:{netloc}")
            finally:
                second.shutdown()
        finally:
            chaos.uninstall()


# --------------------------------- 2-group graceful-vs-SIGKILL A/B drive


class TestGracefulReclaimDrive:
    """The acceptance oracle (ISSUE 14): two groups over a REAL
    socketpair ring (the data plane is real sockets; the control plane
    is scripted). Graceful leg: B gets a reclaim notice, drains at its
    commit boundary (farewell first), and A — whose next quorum round
    reflects the farewell-driven membership cut — commits EVERY step
    with zero vote aborts and zero ring-reset latches. SIGKILL control
    leg: B vanishes without a farewell, A's next round still names B
    (staleness not yet proven), its ring op hits dead sockets, and the
    step aborts — the cost the graceful protocol exists to avoid."""

    K_TOGETHER = 3   # steps both groups run
    K_AFTER = 3      # survivor-only steps after B leaves

    def _survivor_client(self, stale_rounds=0):
        """A's scripted control plane: world 2 while B lives, then —
        after `stale_rounds` rounds that still name B (the SIGKILL
        staleness window) — world 1 under a bumped quorum id."""
        client = MagicMock()
        seq = []
        for s in range(1, self.K_TOGETHER + 1):
            seq.append(quorum_result(
                quorum_id=1, max_rank=0, max_world_size=2,
                replica_rank=0, replica_world_size=2, max_step=s))
        for _ in range(stale_rounds):
            seq.append(quorum_result(
                quorum_id=1, max_rank=0, max_world_size=2,
                replica_rank=0, replica_world_size=2))
        for _ in range(self.K_AFTER + 2):
            seq.append(quorum_result(
                quorum_id=2, max_rank=0, max_world_size=1,
                replica_rank=0, replica_world_size=1))
        client.quorum.side_effect = seq
        client.should_commit.side_effect = \
            lambda rank, step, should_commit, timeout_ms=None: should_commit
        return client

    def _leaver_client(self):
        client = MagicMock()
        client.quorum.side_effect = [
            quorum_result(quorum_id=1, max_rank=1, max_world_size=2,
                          replica_rank=1, replica_world_size=2, max_step=s)
            for s in range(1, self.K_TOGETHER + 1)
        ]
        client.should_commit.side_effect = \
            lambda rank, step, should_commit, timeout_ms=None: should_commit
        return client

    def _grads(self, rank, step):
        rng = np.random.default_rng(100 * rank + step)
        return {"g": np.asarray(rng.normal(size=(64,)), np.float32)}

    def _run_leg(self, graceful, tmp_path):
        from test_manager import _make_test_rings, _wired_comm

        rings = _make_test_rings(2)
        store = FakeStore()
        client_a = self._survivor_client(
            stale_rounds=0 if graceful else 1)
        client_b = self._leaver_client()
        comm_a = _wired_comm(rings[0], 0, 2)
        comm_b = _wired_comm(rings[1], 1, 2)

        # The survivor's world genuinely shrinks at the membership cut:
        # the scripted configure mirrors what the real rendezvous does.
        def configure_a(store_addr, rank, world_size):
            comm_a._rank, comm_a._world = rank, world_size
        comm_a.configure = configure_a

        m_a = make_manager(client_a, comm=comm_a, replica_id="groupA")
        m_b = make_manager(client_b, comm=comm_b, replica_id="groupB")
        m_b._healset_store = ("s:1", store)
        from torchft_tpu.checkpoint_io import AsyncCheckpointer

        m_b.set_durable_target(AsyncCheckpointer(), str(tmp_path))

        committed_a = []
        b_outcome = {}

        def run_b():
            try:
                for k in range(self.K_TOGETHER):
                    m_b.step()
                    m_b.allreduce(self._grads(1, k)).result()
                    if graceful and k == self.K_TOGETHER - 1:
                        # The cloud's reclaim notice lands mid-step:
                        # the boundary below still commits; the drain
                        # fires at the next step()'s post-apply edge.
                        m_b.request_preemption(30.0, reason="reclaim")
                    m_b.should_commit()
                if graceful:
                    try:
                        m_b.step()
                        b_outcome["exit"] = "kept-running"
                    except PreemptedExit:
                        b_outcome["exit"] = "preempted"
                else:
                    # SIGKILL: vanish without farewell/shutdown — the
                    # ring sockets are slammed shut by the main thread.
                    b_outcome["exit"] = "killed"
            except Exception as e:  # noqa: BLE001
                b_outcome["exit"] = f"error: {e!r}"

        tb = threading.Thread(target=run_b, name="groupB")
        tb.start()
        try:
            for k in range(self.K_TOGETHER):
                m_a.step()
                avg = m_a.allreduce(self._grads(0, k)).result()
                assert avg is not None
                committed_a.append(m_a.should_commit())
            tb.join(timeout=30)
            assert not tb.is_alive()
            if not graceful:
                # B's process is gone: its sockets slam shut.
                rings[1].close()
                # Simulate the teardown a dead process gets.
                comm_b.shutdown()
            for k in range(self.K_AFTER + (0 if graceful else 1)):
                m_a.step()
                m_a.allreduce(self._grads(0, 100 + k)).result()
                committed_a.append(m_a.should_commit())
            mx_a = m_a.metrics()
            mx_b = m_b.metrics()
            poisoned = m_a._comm_poisoned
            events_a = m_a.history()
        finally:
            m_a.shutdown()
            if not graceful:
                # B never shut down (it "SIGKILL'd"): reap its threads.
                m_b._executor.shutdown(wait=False, cancel_futures=True)
                m_b._put_executor.shutdown(wait=False)
                m_b._ckpt_server.shutdown()
            for ring in rings:
                try:
                    ring.close()
                except Exception:  # noqa: BLE001
                    pass
        return {"committed_a": committed_a, "mx_a": mx_a, "mx_b": mx_b,
                "store": store, "poisoned": poisoned,
                "events_a": events_a, "b_outcome": b_outcome}

    def test_graceful_leg_zero_aborts_zero_ring_resets(self, tmp_path):
        r = self._run_leg(graceful=True, tmp_path=tmp_path)
        # The survivor committed EVERY step across B's exit.
        assert r["committed_a"] == [True] * len(r["committed_a"])
        assert r["mx_a"]["aborted_steps"] == 0
        # Zero ring-reset latches: no poison, no recovery rendezvous.
        assert r["poisoned"] is False
        assert not [e for e in r["events_a"]
                    if e["event"] == "reconfigure" and e.get("recovery")]
        assert not [e for e in r["events_a"] if e["event"] == "abort"]
        # B drained the full protocol: farewell + final save + tombstone.
        assert r["mx_b"]["graceful_exits_total"] == 1
        assert r["store"].kv["torchft/healset/1"] == b"-1:"
        from torchft_tpu import checkpoint_io

        assert checkpoint_io.recover(str(tmp_path)) is not None
        assert r["b_outcome"]["exit"] == "preempted"

    def test_sigkill_control_leg_costs_at_least_one_abort(self, tmp_path):
        r = self._run_leg(graceful=False, tmp_path=tmp_path)
        # The control leg: >= 1 abort proves the graceful protocol
        # earns its keep (identical storm, only the farewell differs).
        assert r["mx_a"]["aborted_steps"] >= 1
        assert False in r["committed_a"]
        # And the survivor RECOVERS: the last steps commit again.
        assert r["committed_a"][-1] is True
        assert r["mx_b"]["graceful_exits_total"] == 0


# ---------------------------------------------------- bench plumbing


class TestChurnBenchPlumbing:
    def test_hard_kill_helper_tears_down_without_farewell(self):
        """The SIGKILL leg's teardown: sockets/servers die, but NO
        farewell goes out — survivors must observe a crash, or the
        control leg silently measures the graceful protocol twice."""
        import bench

        client = MagicMock()
        client.quorum.return_value = quorum_result()
        client.should_commit.return_value = True
        m = make_manager(client)
        assert boundary(m)
        bench._hard_kill_manager(m)
        assert not client.farewell.called
        assert m.metrics()["graceful_exits_total"] == 0

    def test_churn_goodput_row_carries_churn_rate(self):
        """Every bench_churn_goodput result must carry the churn rate
        its row is stamped with (the satellite contract); frozen here
        so a refactor cannot drop it silently."""
        import inspect

        import bench

        src = inspect.getsource(bench.bench_churn_goodput)
        assert '"churn_pct_per_min": churn_pct_per_min' in src
        # And main() stamps churn_rate on every emitted churn row.
        main_src = inspect.getsource(bench.main)
        assert main_src.count('"churn_rate"') >= 2


# ------------------------------------------- join-storm admission (native)


@requires_native
class TestJoinStormAdmission:
    """The ISSUE-14 join-storm acceptance, against the REAL control
    plane: >= 8 joiners landing inside one coalescing window must be
    admitted as ONE membership delta, and a second wave costs exactly
    one more — reconfigure count grows with WINDOWS, not joiners."""

    def _mk_group(self, lh_addr, name, servers, clients):
        from torchft_tpu import _native
        from torchft_tpu.retry import RetryPolicy

        s = _native.ManagerServer(name, lh_addr, store_addr=f"st-{name}",
                                  bind="127.0.0.1:0", world_size=1,
                                  heartbeat_ms=50)
        c = _native.ManagerClient(s.address(), connect_timeout_ms=10_000,
                                  retry_policy=RetryPolicy(max_attempts=1))
        servers.append(s)
        clients.append(c)
        return c

    def test_two_waves_two_deltas(self):
        from torchft_tpu import _native

        lh = _native.Lighthouse(
            bind="127.0.0.1:0", min_replicas=1,
            join_timeout_ms=150,  # a window-less cut per joiner's pace
            quorum_tick_ms=10, heartbeat_fresh_ms=400,
            eviction_staleness_factor=3, join_window_ms=800)
        servers, clients = [], []
        try:
            seed = self._mk_group(lh.address(), "seed", servers, clients)
            q0 = seed.quorum(rank=0, step=1,
                             checkpoint_server_addr="ckpt-seed",
                             timeout_ms=60_000)
            assert q0.replica_world_size == 1

            def wave(tag, k, seed_step):
                results = [None] * (k + 1)
                threads = []

                def seed_join(idx):
                    results[idx] = seed.quorum(
                        rank=0, step=seed_step,
                        checkpoint_server_addr="ckpt-seed",
                        timeout_ms=60_000)

                def joiner(i, idx):
                    c = self._mk_group(lh.address(), f"{tag}{i:02d}",
                                       servers, clients)
                    results[idx] = c.quorum(
                        rank=0, step=1,
                        checkpoint_server_addr=f"ckpt-{tag}{i}",
                        timeout_ms=60_000)

                for i in range(k):
                    threads.append(threading.Thread(target=joiner,
                                                    args=(i, i)))
                # The seed's re-join starts AFTER a few joiners are in
                # flight: a joiner-less instant would serve it from the
                # fast path (solo membership, world 1) before the storm
                # even opens the window.
                threads.insert(3, threading.Thread(target=seed_join,
                                                   args=(k,)))
                for t in threads:
                    t.start()
                    # Staggered past join_timeout_ms in total: without
                    # the window these arrivals would cut several rounds.
                    time.sleep(0.06)
                for t in threads:
                    t.join(timeout=60)
                    assert not t.is_alive()
                return results

            world0 = 1
            r1 = wave("a", 8, seed_step=2)
            assert all(r is not None for r in r1)
            assert {r.quorum_id for r in r1} == {q0.quorum_id + 1}
            assert {r.replica_world_size for r in r1} == {world0 + 8}

            r2 = wave("b", 8, seed_step=3)
            assert {r.quorum_id for r in r2} == {q0.quorum_id + 2}
            assert {r.replica_world_size for r in r2} == {world0 + 16}

            st = lh.status()
            # 8 joiners per wave -> 7 coalesced beyond the first, twice.
            assert st["joins_coalesced"] >= 14
        finally:
            for s in servers:
                s.shutdown()
            lh.shutdown()


@requires_native
@pytest.mark.slow
@pytest.mark.nightly
class TestControlPlaneChurn256:
    """The title-scale soak: a 256-group fleet on the REAL control
    plane (thin manager/client pairs — the data-plane goodput soak
    runs at bench scale) churns through farewell-leaves + silent kills
    + replacement waves. Gates: the quorum keeps cutting, membership
    tracks the live set, and the membership-delta count grows with
    churn WAVES (leaves coalesce per round, joins per window), not
    with individual members."""

    N = 256
    WAVES = 3
    PER_WAVE = 8

    def test_fleet_survives_wave_churn(self):
        from torchft_tpu import _native
        from torchft_tpu.retry import RetryPolicy

        lh = _native.Lighthouse(
            bind="127.0.0.1:0", min_replicas=1,
            join_timeout_ms=60_000, quorum_tick_ms=5,
            heartbeat_fresh_ms=500, eviction_staleness_factor=6,
            join_window_ms=300)
        groups = {}  # name -> (server, client)
        try:
            def spawn(name):
                s = _native.ManagerServer(
                    name, lh.address(), store_addr=f"st-{name}",
                    bind="127.0.0.1:0", world_size=1, heartbeat_ms=100)
                c = _native.ManagerClient(
                    s.address(), connect_timeout_ms=10_000,
                    retry_policy=RetryPolicy(max_attempts=1))
                groups[name] = (s, c)

            def quorum_all(step, early=()):
                """One quorum round for the whole fleet. ``early``
                names start (and announce) first — replacement waves
                must open the slow round before a survivor's request
                can sneak a fast-path serve of the stale membership."""
                out = {}
                errs = []

                def one(name, c):
                    try:
                        out[name] = c.quorum(
                            rank=0, step=step,
                            checkpoint_server_addr=f"ck-{name}",
                            timeout_ms=120_000)
                    except Exception as e:  # noqa: BLE001
                        errs.append((name, repr(e)))

                ts_early = [threading.Thread(target=one, args=(n, c))
                            for n, (_s, c) in groups.items()
                            if n in early]
                ts = [threading.Thread(target=one, args=(n, c))
                      for n, (_s, c) in groups.items()
                      if n not in early]
                for t in ts_early:
                    t.start()
                if ts_early:
                    time.sleep(0.5)  # announces landed; round is open
                for t in ts:
                    t.start()
                for t in ts_early + ts:
                    t.join(timeout=180)
                assert not errs, errs[:3]
                return out

            for i in range(self.N):
                spawn(f"g{i:03d}")
            r = quorum_all(1)
            qid0 = next(iter(r.values())).quorum_id
            assert {v.replica_world_size for v in r.values()} == {self.N}

            rng = np.random.default_rng(42)
            step = 2
            for wave in range(self.WAVES):
                victims = rng.choice(sorted(groups), size=self.PER_WAVE,
                                     replace=False)
                for j, name in enumerate(victims):
                    s, _c = groups.pop(name)
                    if j % 2 == 0:
                        s.shutdown()   # clean leave: farewell
                    else:
                        s.hard_stop()  # SIGKILL: silence, staleness
                # Survivors cut the shrunken quorum; the farewell'd
                # half is provably gone, the killed half ages out
                # within the staleness bound.
                r = quorum_all(step)
                assert {v.replica_world_size for v in r.values()} \
                    == {self.N - self.PER_WAVE}
                step += 1
                # Replacement wave: fresh ids join inside one window.
                new_names = set()
                for i in range(self.PER_WAVE):
                    spawn(f"r{wave}{i:02d}")
                    new_names.add(f"r{wave}{i:02d}")
                r = quorum_all(step, early=new_names)
                assert {v.replica_world_size for v in r.values()} \
                    == {self.N}
                step += 1

            # Membership-delta accounting: each wave costs O(1) deltas
            # (one shrink cut + one coalesced join round, plus at most
            # one straggler round) — NOT one per preempted/joined
            # member.
            qid_delta = next(iter(r.values())).quorum_id - qid0
            assert qid_delta <= 3 * self.WAVES
            st = lh.status()
            assert st["joins_coalesced"] >= self.WAVES * (self.PER_WAVE // 2)
        finally:
            for s, _c in groups.values():
                s.shutdown()
            lh.shutdown()


# ------------------------------------------------- nightly churn soak


@requires_native
@pytest.mark.slow
@pytest.mark.nightly
class TestChurnSoak:
    """The Poisson churn soak (nightly): seeded graceful+SIGKILL churn
    with cold replacements at accelerated rates, gated on the ISSUE-14
    acceptance — graceful-leg goodput >= 0.8x the zero-churn baseline,
    and bitwise convergence through unbounded membership drift."""

    def test_churn_goodput_curve_and_bitwise_convergence(self):
        import bench

        base = bench.bench_churn_goodput(churn_pct_per_min=0.0,
                                         duration_s=20.0, seed=1234)
        assert base["bitwise_identical"]
        base_rate = base["committed_batches_per_s"]
        assert base_rate > 0

        # Graceful leg walks stable -> storm -> stable (PhasedChaos
        # shape) so the gate covers the regime transition, not just a
        # constant rate.
        graceful = bench.bench_churn_goodput(
            leg="graceful", reclaim_s=8.0, seed=1234,
            phases=((8.0, 0.0), (16.0, 200.0), (8.0, 0.0)))
        assert graceful["notices"] >= 1
        assert graceful["bitwise_identical"]
        assert graceful["committed_batches_per_s"] >= 0.8 * base_rate

        sigkill = bench.bench_churn_goodput(
            churn_pct_per_min=150.0, leg="sigkill", duration_s=30.0,
            seed=1234)
        assert sigkill["kills"] >= 1
        assert sigkill["bitwise_identical"]

    def test_ram_tier_churn_goodput_ab(self):
        """RAM-tier A/B under sustained churn (docs/design/memory_tier.md,
        ISSUE-16 acceptance): the 20%/min leg must hold goodput with the
        RAM tier armed — cross-replication at every commit boundary and
        RAM-preferring cold starts must not cost throughput vs the
        disk-only control, and the bitwise oracle must stay exact."""
        import bench

        off = bench.bench_churn_goodput(
            churn_pct_per_min=20.0, leg="sigkill", duration_s=30.0,
            seed=4321, replace_delay_s=1.0, ram_tier=False)
        assert off["bitwise_identical"]
        assert off["committed_batches_per_s"] > 0

        on = bench.bench_churn_goodput(
            churn_pct_per_min=20.0, leg="sigkill", duration_s=30.0,
            seed=4321, replace_delay_s=1.0, ram_tier=True)
        assert on["ram_tier"]
        assert on["bitwise_identical"]
        # Replication rides the commit boundary on every group, so it
        # must be happening even when churn never fires a kill.
        assert on["ram_replications"] >= 1
        # Goodput gate: RAM-on holds >= 0.9x the disk-only control
        # (replication is async off the step path; the tier may only
        # ever make replacement FASTER, never training slower).
        assert on["committed_batches_per_s"] >= (
            0.9 * off["committed_batches_per_s"])
