"""RAM checkpoint tier tests (docs/design/memory_tier.md): the
in-memory v2 image codec (single-write-pass digests, disk-format
byte compatibility), the staged peer-push accept path (ranged PUTs,
crc-verified before acceptance, 422 on corruption), the bounded
RamCheckpointStore, the RamReplicator demotion pipeline (encode ->
RAM -> K peers -> local disk -> durable, AsyncCheckpointer
discipline: stall watchdog, sticky errors, fatal classification),
the chaos RAM fault band (peer-RAM loss, replication blackhole,
correlated K-peer death), and the Manager integration halves —
commit-coupled dispatch with the save_durable refusal classes,
healset-key peer discovery with tombstone filtering, the
RAM-preferring prejoin/cold-start rungs, and replication-set
collapse detection. All native-free (FakeStore control planes,
real sockets for the byte path); the RAM-on/off churn soak rides
the nightly tier in tests/test_churn.py."""

import os
import threading
import time
from unittest.mock import MagicMock

import numpy as np
import pytest

from test_manager import make_manager, quorum_result
from torchft_tpu import chaos as chaos_mod
from torchft_tpu import checkpoint_io as cio
from torchft_tpu import ram_ckpt
from torchft_tpu.chaos import ChaosSchedule, EndpointChaos
from torchft_tpu.checkpoint_io import CheckpointCorruptError
from torchft_tpu.checkpointing import CheckpointServer
from torchft_tpu.ram_ckpt import (RamCheckpointStore, RamReplicator,
                                  _Stage, encode_image, load_image,
                                  peer_steps, push_image, verify_image)

pytestmark = pytest.mark.ramckpt


def user_state(val=1.0):
    return {
        "params": {"w": np.full((16, 4), val, np.float32),
                   "b": np.zeros(8, np.float32)},
        "opt": [np.ones(3, np.float32), np.int64(4)],
    }


def mgr_state(step):
    return {"step": step, "batches_committed": step * 2}


def make_image(step=1, val=1.0):
    return encode_image(user_state(val), mgr_state(step),
                        meta={"committed": True, "replica_id": "g0"})


def tree_equal(a, b):
    import jax

    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


@pytest.fixture
def peer():
    """A peer host: real CheckpointServer + attached RAM store."""
    store = RamCheckpointStore()
    srv = CheckpointServer(lambda: {"user": {}, "torchft": {}})
    srv.attach_ram_store(store)
    yield srv, store
    srv.shutdown()


@pytest.fixture(autouse=True)
def chaos_reset():
    chaos_mod.reset()
    yield
    chaos_mod.reset()


# ------------------------------------------------------------ image codec


class TestImageCodec:
    def test_round_trip(self):
        img = make_image(step=7, val=3.5)
        assert img.step == 7
        assert img.nbytes == len(img.data) > 0
        user, mgr = load_image(img.data, target=user_state(0.0),
                               device_put=False)
        assert tree_equal(user, user_state(3.5))
        assert mgr["step"] == 7

    def test_verify_rejects_flipped_byte(self):
        img = make_image()
        data = bytearray(img.data)
        data[len(data) // 2] ^= 0xFF
        with pytest.raises(CheckpointCorruptError):
            verify_image(bytes(data))

    def test_image_is_disk_format(self, tmp_path):
        """The demotion invariant: an image written verbatim as
        {prefix}{step} IS a durable v2 checkpoint — recover() and
        load() treat it exactly like a cadence save's file."""
        img = make_image(step=5, val=2.0)
        path = str(tmp_path / "ckpt_5")
        with open(path, "wb") as f:
            f.write(img.data)
        assert cio.recover(str(tmp_path)) == path
        user, mgr = cio.load(path, target=user_state(0.0))
        assert tree_equal(user, user_state(2.0))
        assert mgr["step"] == 5

    def test_transfer_manifest_spelling(self):
        mf = make_image(step=3).transfer_manifest()
        assert mf["format"] == ram_ckpt.TRANSFER_MANIFEST_FORMAT
        assert mf["step"] == 3
        assert mf["leaves"]


# ------------------------------------------------------- staged assembly


class TestStage:
    def test_out_of_order_chunks_complete(self):
        data = bytes(range(256))
        st = _Stage(len(data), "peer")
        st.write(128, data[128:])
        assert not st.complete()
        st.write(0, data[:128])
        assert st.complete()
        assert bytes(st.buf) == data

    def test_overlap_and_repush_idempotent(self):
        data = b"x" * 100
        st = _Stage(100, "peer")
        st.write(0, data[:60])
        st.write(40, data[40:])  # overlaps [40,60)
        assert st.complete()
        st.write(0, data[:10])  # re-push of a done range
        assert st.complete()


# -------------------------------------------------------------- the store


class TestRamCheckpointStore:
    def test_put_get_latest_eviction(self):
        s = RamCheckpointStore(keep=2)
        for step in (1, 2, 3):
            s.put(make_image(step=step))
        assert s.steps() == [2, 3]
        assert s.latest().step == 3
        assert s.get(1) is None
        m = s.metrics()
        assert m["ram_ckpt_images"] == 2.0
        assert m["ram_ckpt_evictions_total"] == 1.0

    def test_put_bytes_verifies(self):
        s = RamCheckpointStore()
        img = make_image(step=4)
        data = bytearray(img.data)
        data[-20] ^= 0x01
        with pytest.raises(CheckpointCorruptError):
            s.put_bytes(bytes(data))
        assert s.steps() == []
        assert s.metrics()["ram_ckpt_rejects_total"] == 1.0
        s.put_bytes(img.data, origin="peer")
        assert s.steps() == [4]

    def test_stage_write_assembles(self):
        s = RamCheckpointStore()
        img = make_image(step=9)
        mid = len(img.data) // 2
        done = s.stage_write(9, 0, img.data[:mid], len(img.data))
        assert not done
        assert s.get(9) is None  # partial is never servable
        done = s.stage_write(9, mid, img.data[mid:], len(img.data))
        assert done
        assert s.get(9).step == 9


# ------------------------------------------------------------- HTTP path


class TestHttpPath:
    def test_push_then_heal_bitwise(self, peer):
        srv, store = peer
        img = make_image(step=6, val=4.25)
        pushed = push_image(srv.ram_address(), img, chunk_bytes=512)
        assert pushed == img.nbytes
        assert store.steps() == [6]
        # The striped digest-verified healer runs UNCHANGED against
        # the RAM tier — the bitwise convergence oracle.
        state = CheckpointServer.load_from_address(
            f"{srv.ram_address()}/ramckpt/6",
            {"user": user_state(0.0), "torchft": mgr_state(0)})
        assert tree_equal(state["user"], user_state(4.25))
        assert state["torchft"]["step"] == 6

    def test_corrupt_push_rejected_422(self, peer):
        srv, store = peer
        img = make_image(step=2)
        data = bytearray(img.data)
        data[len(data) - 30] ^= 0xFF
        img.data = bytes(data)
        with pytest.raises(CheckpointCorruptError):
            push_image(srv.ram_address(), img)
        assert store.steps() == []
        assert store.metrics()["ram_ckpt_rejects_total"] == 1.0

    def test_peer_steps_probe(self, peer):
        srv, store = peer
        assert peer_steps(srv.ram_address()) == []
        store.put(make_image(step=3))
        store.put(make_image(step=5))
        assert peer_steps(srv.ram_address()) == [3, 5]
        assert peer_steps("http://127.0.0.1:9") == []  # dead peer

    def test_auth_gate(self):
        store = RamCheckpointStore()
        srv = CheckpointServer(lambda: {}, auth_token="sekrit")
        srv.attach_ram_store(store)
        try:
            with pytest.raises(OSError):
                push_image(srv.ram_address(), make_image(step=1))
            assert store.steps() == []
            push_image(srv.ram_address(), make_image(step=1),
                       auth_token="sekrit")
            assert store.steps() == [1]
        finally:
            srv.shutdown()


# ----------------------------------------------------------- replicator


class TestRamReplicator:
    def test_pipeline_k_peers_and_demotion(self, peer, tmp_path):
        srv, pstore = peer
        local = RamCheckpointStore()
        demote = str(tmp_path / "local")
        durable = str(tmp_path / "durable")
        os.makedirs(demote)
        os.makedirs(durable)
        rep = RamReplicator(local, peers_fn=lambda: [srv.ram_address()],
                            k=1, demote_dir=demote, durable_dir=durable)
        fut = rep.replicate_image_async(make_image(step=8, val=2.0))
        assert fut.result(timeout=30) == 1
        rep.wait()
        assert local.steps() == [8]
        assert pstore.steps() == [8]
        # Both demotion rungs hold loadable v2 files.
        for d in (demote, durable):
            user, mgr = cio.load(os.path.join(d, "ckpt_8"),
                                 target=user_state(0.0))
            assert mgr["step"] == 8
        m = rep.metrics()
        assert m["ram_ckpt_peers"] == 1.0
        assert m["ram_ckpt_replications_total"] == 1.0
        assert m["ram_ckpt_bytes_replicated_total"] > 0
        assert m["demote_stage_ms_total"] > 0

    def test_dead_peer_skipped(self, peer):
        srv, pstore = peer
        rep = RamReplicator(
            RamCheckpointStore(),
            peers_fn=lambda: ["http://127.0.0.1:9", srv.ram_address()],
            k=1, push_timeout_sec=2.0)
        assert rep.replicate_image_async(
            make_image(step=1)).result(timeout=30) == 1
        assert pstore.steps() == [1]
        m = rep.metrics()
        assert m["ram_ckpt_push_failures_total"] >= 1.0
        assert m["ram_ckpt_peers"] == 1.0

    def test_zero_accepts_is_not_an_error(self):
        rep = RamReplicator(RamCheckpointStore(),
                            peers_fn=lambda: [], k=2)
        assert rep.replicate_image_async(
            make_image(step=1)).result(timeout=30) == 0
        rep.wait()  # no sticky error: local rung still landed
        assert rep.metrics()["ram_ckpt_peers"] == 0.0

    def test_snapshot_encode_path(self, peer):
        srv, pstore = peer
        rep = RamReplicator(RamCheckpointStore(),
                            peers_fn=lambda: [srv.ram_address()], k=1)
        fut = rep.replicate_async(user_state(7.0), mgr_state(11),
                                  meta={"committed": True})
        assert fut.result(timeout=30) == 1
        assert pstore.get(11) is not None
        assert rep.metrics()["demote_encode_ms"] > 0

    def test_demotion_error_is_sticky(self, tmp_path):
        # demote_dir is an existing FILE: makedirs/rename both fail.
        clash = str(tmp_path / "clash")
        with open(clash, "w") as f:
            f.write("x")
        rep = RamReplicator(RamCheckpointStore(), peers_fn=lambda: [],
                            k=0, demote_dir=clash)
        fut = rep.replicate_image_async(make_image(step=1))
        with pytest.raises(Exception):
            fut.result(timeout=30)
        with pytest.raises(RuntimeError):
            rep.wait()  # latched error surfaces exactly once
        rep.wait()
        assert rep.metrics()["ram_demote_errors"] == 1.0
        assert "Error" in (rep.last_error() or "")

    def test_stall_watchdog_abandons(self):
        release = threading.Event()

        def stuck_peers():
            release.wait(10)
            return []

        rep = RamReplicator(RamCheckpointStore(), peers_fn=stuck_peers,
                            k=1, stall_timeout_sec=0.3)
        rep.replicate_image_async(make_image(step=1))
        t0 = time.monotonic()
        with pytest.raises(RuntimeError) as ei:
            rep.wait()
        release.set()
        assert time.monotonic() - t0 < 5
        assert isinstance(ei.value.__cause__, cio.CheckpointStallError)
        assert rep.metrics()["ram_demote_stalls"] == 1.0


# ------------------------------------------------------------ chaos band


class TestRamChaos:
    def test_rate_zero_draws_no_ram_faults(self):
        sched = ChaosSchedule(seed=3, endpoints={
            "ram": EndpointChaos()})
        for _ in range(200):
            d = sched.decide("ram:h:1", "push")
            assert d is None or d.fault is None

    def test_ram_loss_drops_stored_image(self):
        chaos_mod.install(ChaosSchedule(seed=1, endpoints={
            "ram": EndpointChaos(ram_loss_rate=1.0)}))
        try:
            s = RamCheckpointStore(chaos_scope="ram:h:1")
            s.put(make_image(step=4))
            assert s.get(4) is None  # host reclaimed the RAM
            assert s.metrics()["ram_ckpt_losses_total"] >= 1.0
        finally:
            chaos_mod.uninstall()

    def test_blackhole_fails_push(self, peer):
        srv, pstore = peer
        chaos_mod.install(ChaosSchedule(seed=2, endpoints={
            "ram": EndpointChaos(ram_blackhole_rate=1.0,
                                 blackhole_ms=10)}))
        try:
            with pytest.raises(OSError):
                push_image(srv.ram_address(), make_image(step=1))
            assert pstore.steps() == []
        finally:
            chaos_mod.uninstall()

    def test_correlated_peer_death_latches(self, peer):
        """Kill latch = correlated K-peer death: every peer in the
        replication set dies, pushes fail, accepts drop to zero — and
        a reborn server at the same netloc clears the latch."""
        srv, pstore = peer
        sched = ChaosSchedule(seed=0, endpoints={
            "ram": EndpointChaos()})
        chaos_mod.install(sched)
        try:
            import urllib.parse

            netloc = urllib.parse.urlsplit(srv.ram_address()).netloc
            sched.kill_endpoint(f"ram:{netloc}")
            rep = RamReplicator(
                RamCheckpointStore(),
                peers_fn=lambda: [srv.ram_address()], k=1)
            assert rep.replicate_image_async(
                make_image(step=1)).result(timeout=30) == 0
            assert rep.metrics()["ram_ckpt_peers"] == 0.0
            sched.revive_endpoint(f"ram:{netloc}")
            assert rep.replicate_image_async(
                make_image(step=2)).result(timeout=30) == 1
        finally:
            chaos_mod.uninstall()


# ----------------------------------------------------- Manager coupling


class FakeStore:
    """Dict-backed stand-in for the native StoreClient, injected via
    the Manager's per-address store-client cache."""

    def __init__(self):
        self.kv = {}
        self.lock = threading.Lock()

    def set(self, key, value):
        with self.lock:
            self.kv[key] = value if isinstance(value, bytes) \
                else str(value).encode()

    def get(self, key, timeout_ms=0):
        with self.lock:
            if key not in self.kv:
                raise KeyError(key)
            return self.kv[key]


def ram_manager(peers=1, state=None, **kw):
    client = MagicMock()
    client.quorum.return_value = quorum_result(store_address="fake:1")
    client.should_commit.return_value = True
    st = (state if state is not None
          else {"w": np.arange(8, dtype=np.float32)})
    m = make_manager(client, use_async_quorum=False, min_replica_size=1,
                     load_state_dict=lambda s: st.update(s),
                     state_dict=lambda: st,
                     ram_ckpt_peers=peers, **kw)
    # Pre-seed the per-address store-client cache so healset
    # publication/discovery against "fake:1" never dials a native
    # client (the churn tests' injection idiom).
    m._healset_store = ("fake:1", FakeStore())
    return m, client, st


def wire_peer(m, srv, rank=1, step=1):
    fs = m._healset_store[1]
    fs.set(f"torchft/healset/{rank}", f"{step}:{srv.address()}".encode())
    return fs


def boundary(m):
    m.step()
    m.allreduce({"g": np.ones(4, np.float32)}).result()
    return m.should_commit()


class TestManagerRamTier:
    def test_ctor_and_env_arming(self, monkeypatch):
        m, _, _ = ram_manager(peers=2)
        assert m.ram_tier_enabled()
        m.shutdown()
        monkeypatch.setenv("TORCHFT_RAM_CKPT_PEERS", "1")
        m2, _, _ = ram_manager(peers=None)
        assert m2.ram_tier_enabled()
        m2.shutdown()
        monkeypatch.delenv("TORCHFT_RAM_CKPT_PEERS")
        m3, _, _ = ram_manager(peers=None)
        assert not m3.ram_tier_enabled()
        m3.shutdown()

    def test_step_boundary_replicates_to_discovered_peer(self, peer):
        srv, pstore = peer
        m, _, _ = ram_manager(peers=1)
        wire_peer(m, srv)
        try:
            for _ in range(3):
                assert boundary(m)
            m._ram_replicator.wait()
            assert pstore.steps()  # the commit images crossed the wire
            mx = m.metrics()
            assert mx["ram_ckpt_peers"] == 1.0
            assert mx["ram_ckpt_bytes_replicated_total"] > 0
            assert mx["ram_replicate_skipped"] == 0.0
        finally:
            m.shutdown()

    def test_tombstoned_peer_never_a_push_target(self, peer):
        srv, _ = peer
        m, _, _ = ram_manager(peers=1)
        fs = wire_peer(m, srv)
        fs.set("torchft/healset/1", b"-1:")  # withdrawn (PR 14)
        try:
            assert boundary(m)
            assert m._ram_peer_bases() == []
        finally:
            m.shutdown()

    def test_refusal_classes(self):
        m, client, _ = ram_manager(peers=1)
        try:
            assert boundary(m)
            # Latched error: the state may be mid-apply — refuse.
            m._errored = RuntimeError("boom")
            assert m.replicate_ram() is None
            m._errored = None
            # Healing: staged/unapplied state — refuse.
            with m._metrics_lock:
                m._healing = True
            assert m.replicate_ram() is None
            with m._metrics_lock:
                m._healing = False
            # Aborted vote: nothing committed — refuse.
            m._should_step = False
            assert m.replicate_ram() is None
            m._should_step = True
            assert m.metrics()["ram_replicate_skipped"] == 3.0
            events = [e["event"] for e in m.history()]
            assert events.count("ram_replicate_skip") == 3
        finally:
            m.shutdown()

    def test_replication_set_collapse_dumps_once(self, peer):
        srv, _ = peer
        m, _, _ = ram_manager(peers=1)
        wire_peer(m, srv)
        try:
            assert boundary(m)
            assert boundary(m)  # first boundary with a discovered peer
            m._ram_replicator.wait()
            assert m.metrics()["ram_ckpt_peers"] == 1.0
            srv.shutdown()  # the whole replication set dies
            for _ in range(4):
                assert boundary(m)
                m._ram_replicator.wait()
            mx = m.metrics()
            assert mx["ram_ckpt_peers"] == 0.0
            assert mx["ram_replica_collapses_total"] == 1.0  # one-shot
            assert any(e["event"] == "ram_replica_collapse"
                       for e in m.history())
        finally:
            m.shutdown()

    def test_cold_start_prefers_ram_rung(self, peer, tmp_path):
        srv, pstore = peer
        # Disk rung: a committed step-2 file; RAM rung: step 5.
        cio.save(str(tmp_path / "ckpt_2"), user_state(1.0),
                 mgr_state(2), meta={"committed": True})
        pstore.put(encode_image({"w": np.full(8, 9.0, np.float32)},
                                {"step": 5, "batches_committed": 10},
                                meta={"committed": True}))
        st = {"w": np.zeros(8, np.float32)}
        m, _, _ = ram_manager(peers=0, state=st)
        try:
            src = m.cold_start(str(tmp_path),
                               ram_peers=[srv.ram_address()])
            assert src.endswith("/ramckpt/5")
            assert np.array_equal(st["w"], np.full(8, 9.0, np.float32))
            assert m.current_step() == 5
            assert m.metrics()["ram_ckpt_heals_total"] == 1.0
        finally:
            m.shutdown()

    def test_cold_start_falls_back_to_disk(self, tmp_path):
        cio.save(str(tmp_path / "ckpt_3"),
                 {"w": np.full(8, 3.0, np.float32)}, mgr_state(3),
                 meta={"committed": True})
        st = {"w": np.zeros(8, np.float32)}
        m, _, _ = ram_manager(peers=0, state=st)
        try:
            src = m.cold_start(str(tmp_path),
                               ram_peers=["http://127.0.0.1:9"])
            assert src == str(tmp_path / "ckpt_3")  # dead peers -> disk
            assert np.array_equal(st["w"], np.full(8, 3.0, np.float32))
            assert m.metrics()["ram_ckpt_heals_total"] == 0.0
        finally:
            m.shutdown()

    def test_prejoin_heal_uses_ram_rung(self, peer):
        srv, pstore = peer
        fleet_step = 4
        pstore.put(encode_image({"w": np.full(8, 2.5, np.float32)},
                                {"step": fleet_step,
                                 "batches_committed": 8},
                                meta={"committed": True}))
        st = {"w": np.zeros(8, np.float32)}
        m, _, _ = ram_manager(peers=1, state=st)
        try:
            ok = m.prejoin_heal(
                fleet=lambda: {"members": [
                    {"step": fleet_step, "address": "m1:1"}]},
                resolve=lambda a: srv.address())
            assert ok
            assert np.array_equal(st["w"], np.full(8, 2.5, np.float32))
            mx = m.metrics()
            assert mx["prejoin_heals_total"] == 1.0
            assert mx["ram_ckpt_heals_total"] == 1.0
        finally:
            m.shutdown()

    def test_drain_withdraws_ram_tier(self, peer):
        srv, _ = peer
        m, _, _ = ram_manager(peers=1)
        wire_peer(m, srv)
        try:
            assert boundary(m)
            assert m._ckpt_server.ram_address()
            m._withdraw_advertisements()
            # Detached: the local /ramckpt stops serving.
            assert peer_steps(m._ckpt_server.ram_address()) == []
        finally:
            m.shutdown()

    def test_metrics_expose_tier_counters(self):
        m, _, _ = ram_manager(peers=1)
        try:
            mx = m.metrics()
            for key in ("ram_ckpt_heals_total", "ram_replicate_skipped",
                        "ram_replicate_errors_total",
                        "ram_replica_collapses_total", "ram_ckpt_peers",
                        "ram_ckpt_bytes_replicated_total",
                        "demote_stage_ms_total", "ram_ckpt_images",
                        "ram_ckpt_accepts_total"):
                assert key in mx, key
        finally:
            m.shutdown()


class TestRecoveryTiersBench:
    """ISSUE-16 acceptance, at tiny scale: bench_recovery_tiers must
    show the RAM rung healing >= 2x faster than the disk-only rung
    under a rate-capped disk, ending bitwise identical on both legs."""

    def test_ram_rung_beats_rate_capped_disk(self):
        import bench

        row = bench.bench_recovery_tiers(payload_mb=8.0,
                                         disk_mb_s=32.0,
                                         nic_mb_s=250.0)
        assert row["bitwise_identical"]
        assert row["ram_speedup"] >= 2.0, row
        assert row["disk_wall_s"] > row["ram_wall_s"]
